"""Process-domain analysis: which code runs in WHICH process (HSL019-022).

PRs 11-12 made the system a genuinely multi-process installation — a
fleet supervisor spawning serving workers (serve/fleet/supervisor.py),
a spawn-context task pool for the scale-out build
(parallel/procpool.py), a spill-file exchange between build workers
(execution/build_exchange.py), and cross-process file leases
(serve/fleet/lease.py). Every invariant that makes those paths correct
was enforced by convention: workers never import jax at module load,
only paths and primitives cross the process boundary, shared files
publish atomically under leases, and fault rules / trace roots ship
across the boundary. This module turns the conventions into checked
facts, on top of one new piece of infrastructure:

- **The spawn-domain inference.** :data:`SPAWN_ENTRY_POINTS` declares
  every function that runs FIRST inside a spawned worker process (the
  registry is AST-extracted from any scanned module, exactly like
  ``exceptions.ERROR_CONTRACTS`` — fixture packages declare their own).
  Each entry carries a *kind*:

  ========== =========================================================
  ``task``         a carrier shim with a result channel (procpool's
                   ``_task_entry``): must install shipped fault state
                   and its module must merge observed points + adopt
                   trace roots back (HSL022)
  ``task_body``    a task payload dispatched through a carrier
                   (``p1_shard``/``p2_owner``): seeds the call-graph
                   closure — everything it can reach runs in a worker
  ``service``      a long-lived worker-main shim (the fleet
                   supervisor's ``_worker_entry``): must install
                   shipped fault state; telemetry flows through the
                   worker's own health plane, so no merge-back is
                   required and the call graph is NOT followed (the
                   service body boots the full engine on purpose)
  ``service_body`` a service worker main (``_fleet_worker``): checked
                   for module-load purity only — the engine it boots
                   lives behind deferred imports by design
  ========== =========================================================

  The *task domain* is the dispatch-augmented call-graph closure of the
  task/task_body entries; the *domain module set* is every module
  hosting a domain function (any kind) closed over the **module-level
  import graph** (imports inside function bodies — the deferred-import
  idiom — are runtime edges, not load-time edges, and stay out of it;
  ``if TYPE_CHECKING:`` blocks never execute and are skipped).

- **HSL019 spawn-import purity.** No module in the domain module set
  may import jax/jaxlib (pallas included — it lives under
  ``jax.experimental``) at module level. A spawned worker imports the
  entry point's module (to unpickle the target) before running any
  task, so the PR 12 claim "workers never pay the jax import" is
  exactly this closure being jax-free — now a proof with an
  entry-point → import-chain witness instead of a docstring promise.
  Per-function deferred imports stay legal (PR 8's per-function import
  collection keeps them visible to the call graph).

- **HSL020 exchange-surface typing.** Values crossing a process
  boundary — ``TaskPool.submit`` task args, ``ProcessHost.spawn`` /
  ``FleetSupervisor``/``mp.Process`` target args, queue ``put``\\ s
  inside task-domain code, and the return expressions of task bodies —
  must come from the picklable vocabulary (paths, primitives, plain
  dict/list/tuple displays, ``faults.export_state()`` dicts, span
  ``to_json()`` dicts). A ColumnTable, a live ``Span``, a threading
  lock, an open file handle, or a jax value provably flowing in is a
  finding, typed through the same local/attribute bindings the call
  graph resolves receivers with (under-approximate: an expression the
  engine cannot type passes — no false positives from ignorance).

- **HSL021 shared-file protocol.** In domain or fleet modules, a
  write-mode ``open()``/``write_text``/``write_bytes``/``os.open`` on
  a path naming the shared planes (lease/exchange/fleet/spill/evict)
  must sit in a function using the atomic publish idiom (``mkstemp`` +
  ``os.replace``/``os.link``) or claim via ``O_CREAT|O_EXCL`` — the
  generalization of HSL006 beyond the metadata plane. And every
  ``O_EXCL`` lease acquire must reach, through the call graph, a
  TTL-reap/release construct (a function comparing against a
  ttl/stale bound and unlinking/renaming the lease) — witness chains
  like HSL009/HSL018, so a crashed holder provably cannot wedge the
  fleet.

- **HSL022 cross-boundary continuity.** The registry contract in both
  directions (every statically detected spawn target must be declared,
  mirroring HSL012), the carrier plumbing per kind (above), and the
  worker telemetry vocabulary: every span/trace name a task-domain
  function can emit must be declared in ``obs.trace
  KNOWN_WORKER_SPANS``, every counter in ``stats.KNOWN_COUNTERS``,
  every event in ``obs.events.KNOWN_EVENTS`` — a worker can never
  silently lose injected faults or ship telemetry the coordinator's
  registries don't know.

Everything here is stdlib-``ast`` only and never imports analyzed code,
same as the rest of the engine (docs/static_analysis.md).
"""

from __future__ import annotations

import ast
import dataclasses

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.lint import Finding, _dotted
from hyperspace_tpu.analysis.program import FunctionInfo, ModuleInfo, Program

SPAWN_IMPORT = "HSL019"
EXCHANGE_TYPING = "HSL020"
SHARED_FILE = "HSL021"
CONTINUITY = "HSL022"

#: The real registry: every function that runs FIRST in a spawned
#: worker process of this package (and of the scanned benchmark
#: surfaces). AST-extracted from this module when the package is
#: scanned — fixture packages and corpus files declare their own
#: ``SPAWN_ENTRY_POINTS`` literal the same way (the ERROR_CONTRACTS
#: pattern). Keep it a plain dict literal of string constants.
SPAWN_ENTRY_POINTS = {
    # TaskPool's worker entry: installs the coordinator's shipped fault
    # rules, runs the task body, posts exactly one result envelope.
    "hyperspace_tpu.parallel.procpool._task_entry": (
        "task", "TaskPool worker shim: fault state in, observed points + trace root back"),
    # The scale-out build's task bodies (execution/builder.py submits
    # them): everything they can reach runs in a worker process.
    "hyperspace_tpu.execution.build_exchange.p1_shard": (
        "task_body", "p1 shard worker: decode, hash/partition, spill"),
    "hyperspace_tpu.execution.build_exchange.p2_owner": (
        "task_body", "p2 owner worker: spill read, key sort, bucket write"),
    # The fleet supervisor's worker-main shim: long-lived serving
    # workers whose telemetry flows through their own health plane.
    "hyperspace_tpu.serve.fleet.supervisor._worker_entry": (
        "service", "fleet worker shim: fault state in; /metrics + /healthz carry telemetry"),
    # Fleet worker mains spawned by the scanned benchmark harness.
    "benchmarks.bench_serve._fleet_worker": (
        "service_body", "bench fleet member: session + QueryServer behind deferred imports"),
    "benchmarks.bench_serve._bench_lease_holder": (
        "service_body", "bench single-flight holder killed mid-build by the takeover regime"),
    "benchmarks.bench_soak._soak_fleet_worker": (
        "service_body", "soak fleet member: jax-free slot holder SIGKILLed by the respawn episode"),
    # The continuous-ingestion daemon's optional process mode
    # (hyperspace.ingest.processWorker): the whole poll loop runs in a
    # spawn-context worker whose pause/stop controls ride atomic files
    # under <system_path>/_ingest, so a SIGKILL leaves at most a
    # transient log the next recover() converges.
    "hyperspace_tpu.ingest.daemon._service_entry": (
        "service", "ingest worker shim: fault/journal state in; commits via the two-phase Action"),
}

# Module-level imports that may never be reachable at worker start:
# jax and everything under it (pallas lives in jax.experimental), and
# jaxlib. A worker that pays these at import time loses the PR 12
# interpreter-start budget and may touch a device before the task runs.
_BANNED_IMPORT_ROOTS = ("jax", "jaxlib")

# Crossing-value deny list (HSL020): program classes that must never be
# pickled across the process boundary, by simple name. ColumnTable
# ships as spill FILES, Span as its to_json() dict; pools/hosts own OS
# resources; executors own threads.
_BANNED_CROSSING_CLASSES = {
    "ColumnTable", "Span", "TaskPool", "ProcessHost", "FleetSupervisor",
    "ThreadPoolExecutor",
}
# Constructors whose result is an open OS handle.
_OPEN_HANDLE_CTORS = {"open", "fdopen", "NamedTemporaryFile", "TemporaryFile", "mkstemp"}
# Call tails that CONVERT a value into the picklable vocabulary.
_OK_CONVERTERS = {
    "export_state", "to_json", "str", "int", "float", "bool", "list",
    "dict", "tuple", "set", "sorted", "repr", "len", "observed_points",
    "format_exc", "enabled", "snapshot",
}

# Shared-plane path markers (HSL021): expression text naming the
# cross-process file planes. Deliberately narrower than HSL006's
# metadata markers — spill parquet written through ParquetWriter is
# single-writer scratch behind the p1/p2 barrier and is not an open()
# call anyway, and "fleet_dir" (not bare "fleet") keeps single-writer
# artifacts like BENCH_FLEET.json out of the rule.
_SHARED_PATH_MARKERS = ("lease", "exchange", "fleet_dir", "spill", "evict", "reap", "entry_path")


def _suppressed(mod: ModuleInfo, line: int, rule: str) -> bool:
    lines = mod.lines
    text = lines[line - 1] if 0 < line <= len(lines) else ""
    if "# noqa" not in text:
        return False
    tail = text.split("# noqa", 1)[1]
    return not tail.strip().startswith(":") or rule in tail


# -- registry extraction -------------------------------------------------------

def declared_entry_points(program: Program) -> dict[str, tuple[str, str]]:
    """qname -> (kind, why), AST-extracted from every scanned module's
    top-level ``SPAWN_ENTRY_POINTS`` dict literal (the real registry
    lives in analysis/procdomain.py, which the default scan covers;
    fixture packages declare their own)."""
    out: dict[str, tuple[str, str]] = {}
    for mod in program.modules.values():
        for node in mod.tree.body:
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not (isinstance(target, ast.Name) and target.id == "SPAWN_ENTRY_POINTS"):
                continue
            if not isinstance(value, ast.Dict):
                continue
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                kind, why = "task_body", ""
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    kind = v.value
                elif isinstance(v, (ast.Tuple, ast.List)) and v.elts:
                    parts = [e.value for e in v.elts
                             if isinstance(e, ast.Constant) and isinstance(e.value, str)]
                    if parts:
                        kind = parts[0]
                        why = parts[1] if len(parts) > 1 else ""
                out[k.value] = (kind, why)
    return out


def _string_tuple_registry(program: Program, name: str) -> set[str] | None:
    """The union of every scanned module's top-level ``<name>`` tuple of
    string constants, or None when no module declares one (the check
    that reads it disarms — a corpus file scanned alone must not report
    every name undeclared)."""
    out: set[str] | None = None
    for mod in program.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Name) and tgt.id == name):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                out = out or set()
                out.update(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return out


def _known_events(program: Program) -> set[str] | None:
    """Keys of any scanned module's top-level ``KNOWN_EVENTS`` dict."""
    out: set[str] | None = None
    for mod in program.modules.values():
        for node in mod.tree.body:
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not (isinstance(target, ast.Name) and target.id == "KNOWN_EVENTS"):
                continue
            if isinstance(value, ast.Dict):
                out = out or set()
                out.update(
                    k.value for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                )
    return out


# -- module-level import graph -------------------------------------------------

def _is_type_checking_if(node: ast.If) -> bool:
    return any(
        isinstance(sub, (ast.Name, ast.Attribute))
        and (getattr(sub, "id", None) == "TYPE_CHECKING"
             or getattr(sub, "attr", None) == "TYPE_CHECKING")
        for sub in ast.walk(node.test)
    )


def module_level_imports(mod: ModuleInfo) -> list[tuple[str, int]]:
    """(dotted module target, line) for every import that EXECUTES at
    module load: top-level statements plus module-level ``if``/``try``
    bodies and class bodies, excluding function/lambda bodies (deferred
    imports are runtime edges) and ``if TYPE_CHECKING:`` blocks (never
    executed)."""
    out: list[tuple[str, int]] = []

    def walk(body) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.If):
                if not _is_type_checking_if(node):
                    walk(node.body)
                walk(node.orelse)
                continue
            if isinstance(node, ast.Try):
                walk(node.body)
                for h in node.handlers:
                    walk(h.body)
                walk(node.orelse)
                walk(node.finalbody)
                continue
            if isinstance(node, ast.ClassDef):
                walk(node.body)
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out.append((alias.name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = node.module or ""
                else:
                    base = ".".join(
                        mod.name.split(".")[: -node.level]
                        + ([node.module] if node.module else [])
                    )
                if base:
                    out.append((base, node.lineno))
                    # `from pkg import submod` imports pkg.submod too.
                    for alias in node.names:
                        out.append((f"{base}.{alias.name}", node.lineno))
    walk(mod.tree.body)
    return out


def _banned_root(target: str) -> str | None:
    root = target.split(".")[0]
    return root if root in _BANNED_IMPORT_ROOTS else None


# -- the domain ----------------------------------------------------------------

@dataclasses.dataclass
class BoundarySite:
    """One place a value crosses a process boundary."""

    fn: str
    line: int
    kind: str  # submit | spawn | fleet_target | mp_process | put | return
    target: str | None = None  # resolved spawn-target qname, when any
    #: the AST expressions whose values actually cross
    crossing: list = dataclasses.field(default_factory=list)


class ProcessDomains:
    """Spawn-domain inference + the HSL019-022 rules over a Program."""

    def __init__(self, program: Program, callgraph: CallGraph, raises=None):
        self.program = program
        self.callgraph = callgraph
        self.raises = raises  # for dispatch-augmented closure (may-analysis)
        self.entry_points = declared_entry_points(program)
        #: entries that name a scanned function
        self.live_entries: dict[str, tuple[str, str]] = {
            q: kw for q, kw in self.entry_points.items() if q in program.functions
        }
        #: task-domain functions (call-graph closure) -> witness chain
        #: from the seeding entry point
        self.task_fns: dict[str, tuple[str, ...]] = {}
        #: every domain function (task closure + service shims/bodies)
        self.domain_fns: set[str] = set()
        #: domain modules -> ("entry"|"hosts"|importer module, line)
        self.domain_modules: dict[str, tuple[str, int]] = {}
        #: boundary crossings (HSL020 working set + report material)
        self.boundary_sites: list[BoundarySite] = []
        #: O_EXCL acquire sites -> reap witness chain or None
        self.lease_acquires: list[dict] = []
        self._build_closure()
        self._build_module_set()
        self._find_boundaries()

    # -- closure -----------------------------------------------------------
    def _dispatch(self, callee: str) -> tuple[str, ...]:
        if self.raises is not None:
            return self.raises.dispatch_targets(callee)
        return (callee,)

    def _build_closure(self) -> None:
        prog, cg = self.program, self.callgraph
        roots = [
            q for q, (kind, _) in sorted(self.live_entries.items())
            if kind in ("task", "task_body")
        ]
        stack: list[str] = []
        for r in roots:
            self.task_fns[r] = (r,)
            stack.append(r)
        while stack:
            q = stack.pop()
            fn = prog.functions.get(q)
            if fn is None:
                continue
            for call in fn.calls:
                callee = cg.resolve_call(fn, call.raw)
                if callee is None:
                    continue
                for t in self._dispatch(callee):
                    if t in prog.functions and t not in self.task_fns:
                        self.task_fns[t] = (*self.task_fns[q], t)
                        stack.append(t)
        self.domain_fns = set(self.task_fns)
        self.domain_fns.update(
            q for q, (kind, _) in self.live_entries.items()
            if kind in ("service", "service_body")
        )

    def _build_module_set(self) -> None:
        prog = self.program
        seeds: dict[str, tuple[str, int]] = {}
        for q in sorted(self.domain_fns):
            fn = prog.functions[q]
            seeds.setdefault(fn.module, ("hosts " + q, fn.line))
        # Close over the module-level import graph (program-internal
        # edges; external targets are leaves checked by HSL019).
        # Importing `a.b.c` also EXECUTES a/__init__ and a.b/__init__ —
        # the runtime-mirror test caught exactly this hole (a package
        # __init__ eagerly re-exporting a jax module made every worker
        # pay the import the leaf modules carefully deferred), so every
        # ancestor package joins the closure with its child as witness.
        self.domain_modules = dict(seeds)
        stack = list(seeds)

        def add(target: str, via: str, line: int) -> None:
            if target in prog.modules and target not in self.domain_modules:
                self.domain_modules[target] = (via, line)
                stack.append(target)
            parts = target.split(".")
            for i in range(1, len(parts)):
                anc = ".".join(parts[:i])
                if anc in prog.modules and anc not in self.domain_modules:
                    # the ancestor's package __init__ runs because the
                    # CHILD was imported — the child is the witness
                    self.domain_modules[anc] = (target, 0)
                    stack.append(anc)

        for m in list(seeds):
            add(m, seeds[m][0], seeds[m][1])
        while stack:
            m = stack.pop()
            mod = prog.modules.get(m)
            if mod is None:
                continue
            for target, line in module_level_imports(mod):
                add(target, m, line)

    def _module_chain(self, m: str) -> list[str]:
        """Witness: the module-level import chain from a hosting module
        down to `m` (each step recorded at closure time)."""
        chain = [m]
        seen = {m}
        while True:
            via, _ = self.domain_modules.get(chain[-1], ("", 0))
            if not via or via.startswith("hosts ") or via in seen:
                break
            chain.append(via)
            seen.add(via)
        return list(reversed(chain))

    def _entry_for_module(self, m: str) -> str:
        """One entry point whose worker imports module `m` at start."""
        chain = self._module_chain(m)
        host = chain[0]
        via, _ = self.domain_modules.get(host, ("", 0))
        if via.startswith("hosts "):
            q = via[len("hosts "):]
            if q in self.task_fns:
                return self.task_fns[q][0]
            return q
        return host

    # -- boundary sites ----------------------------------------------------
    def _find_boundaries(self) -> None:
        prog, cg = self.program, self.callgraph
        for fn in sorted(prog.functions.values(), key=lambda f: (f.module, f.line)):
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                raw = _dotted(node.func)
                if not raw and isinstance(node.func, ast.Attribute):
                    base = node.func.value
                    if isinstance(base, ast.Call):
                        ctor = _dotted(base.func)
                        if ctor:
                            raw = f"{ctor}().{node.func.attr}"
                if not raw:
                    continue
                resolved = cg.resolve_call(fn, raw)
                tail2 = tuple(resolved.split(".")[-2:]) if resolved else ()
                site = None
                if tail2 == ("TaskPool", "submit"):
                    target = self._fn_ref(fn, node.args[1]) if len(node.args) >= 2 else None
                    site = BoundarySite(fn.qname, node.lineno, "submit", target)
                    site.crossing = list(node.args[2:]) + [kw.value for kw in node.keywords]
                elif tail2 == ("ProcessHost", "spawn"):
                    target = self._fn_ref(fn, node.args[1]) if len(node.args) >= 2 else None
                    site = BoundarySite(fn.qname, node.lineno, "spawn", target)
                    crossing = [a for a in node.args[2:]]
                    for kw in node.keywords:
                        if kw.arg == "args":
                            crossing.append(kw.value)
                    site.crossing = self._splat_tuples(crossing)
                elif raw.split(".")[-1] == "FleetSupervisor":
                    # Detected by ctor NAME: the supervisor is re-exported
                    # through the fleet package, which the deliberately
                    # under-approximate resolver does not chase for ctor
                    # expressions — and a missed fleet spawn would silently
                    # skip the whole domain proof for that worker.
                    target = self._fn_ref(fn, node.args[0]) if node.args else None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = self._fn_ref(fn, kw.value)
                    site = BoundarySite(fn.qname, node.lineno, "fleet_target", target)
                    site.crossing = self._splat_tuples(
                        [kw.value for kw in node.keywords if kw.arg == "args"]
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Process"
                    and any(kw.arg == "target" for kw in node.keywords)
                ):
                    target = next(
                        (self._fn_ref(fn, kw.value) for kw in node.keywords
                         if kw.arg == "target"), None,
                    )
                    site = BoundarySite(fn.qname, node.lineno, "mp_process", target)
                    site.crossing = self._splat_tuples(
                        [kw.value for kw in node.keywords if kw.arg == "args"]
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "put"
                    and fn.qname in self.task_fns
                ):
                    site = BoundarySite(fn.qname, node.lineno, "put")
                    site.crossing = list(node.args)
                if site is not None:
                    self.boundary_sites.append(site)
            # Task bodies: their return values cross back through the
            # result queue.
            if fn.qname in self.task_fns and self._entry_kind(fn.qname) == "task_body":
                for node in self._own_returns(fn):
                    if node.value is None:
                        continue
                    site = BoundarySite(fn.qname, node.lineno, "return")
                    site.crossing = [node.value]
                    self.boundary_sites.append(site)

    def _entry_kind(self, qname: str) -> str | None:
        got = self.live_entries.get(qname)
        return got[0] if got else None

    @staticmethod
    def _own_returns(fn: FunctionInfo):
        """Return statements of `fn` itself (nested defs excluded)."""
        nested: set[int] = set()
        for sub in ast.walk(fn.node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) and sub is not fn.node:
                for inner in ast.walk(sub):
                    nested.add(id(inner))
        return [
            n for n in ast.walk(fn.node)
            if isinstance(n, ast.Return) and id(n) not in nested
        ]

    @staticmethod
    def _splat_tuples(exprs: list) -> list:
        out = []
        for e in exprs:
            if isinstance(e, (ast.Tuple, ast.List)):
                out.extend(e.elts)
            else:
                out.append(e)
        return out

    def _fn_ref(self, fn: FunctionInfo, expr: ast.expr) -> str | None:
        """The program-function qname a bare/dotted reference names (a
        spawn target passed BY REFERENCE, not called)."""
        raw = _dotted(expr)
        if not raw:
            return None
        got = self.callgraph.resolve_call(fn, raw)
        return got if got in self.program.functions else None

    # -- HSL019: spawn-import purity --------------------------------------
    def spawn_import_findings(self) -> list[Finding]:
        prog = self.program
        findings: list[Finding] = []
        seen: set[tuple[str, int, str]] = set()
        for m in sorted(self.domain_modules):
            mod = prog.modules.get(m)
            if mod is None:
                continue
            for target, line in module_level_imports(mod):
                root = _banned_root(target)
                if root is None or _suppressed(mod, line, SPAWN_IMPORT):
                    continue
                if (m, line, root) in seen:
                    continue  # `from jax import x, y` is one finding
                seen.add((m, line, root))
                chain = self._module_chain(m)
                entry = self._entry_for_module(m)
                via = " imports ".join(chain) if len(chain) > 1 else m
                witness = tuple(
                    prog.modules[c].path for c in chain if c in prog.modules
                )
                findings.append(Finding(
                    mod.path, line, 0, SPAWN_IMPORT,
                    f"module-level import of {target!r} is reachable at worker "
                    f"start from spawn entry point {entry} ({via}) — a spawned "
                    f"worker pays the {root} import before any task runs; defer "
                    f"it into the function that needs it (spawn-import purity, "
                    f"docs/static_analysis.md)",
                    witness_paths=witness,
                ))
        return findings

    # -- HSL020: exchange-surface typing -----------------------------------
    def exchange_typing_findings(self) -> list[Finding]:
        prog = self.program
        findings: list[Finding] = []
        for site in self.boundary_sites:
            fn = prog.functions.get(site.fn)
            mod = prog.modules.get(fn.module) if fn is not None else None
            if fn is None or mod is None:
                continue
            for expr in getattr(site, "crossing", []):
                bad = self._crossing_violation(fn, expr)
                if bad is None:
                    continue
                line = getattr(expr, "lineno", site.line)
                if _suppressed(mod, line, EXCHANGE_TYPING):
                    continue
                witness = ()
                if site.fn in self.task_fns:
                    witness = tuple(
                        prog.modules[prog.functions[q].module].path
                        for q in self.task_fns[site.fn]
                        if q in prog.functions
                    )
                findings.append(Finding(
                    mod.path, line, 0, EXCHANGE_TYPING,
                    f"{bad} crosses the process boundary at {site.fn} "
                    f"({site.kind} site) — only paths, primitives, plain "
                    f"dict/list displays, faults.export_state() dicts and span "
                    f"to_json() dicts may cross (exchange-surface typing, "
                    f"docs/static_analysis.md); ship a path or a plain-data "
                    f"snapshot instead",
                    witness_paths=witness,
                ))
        return findings

    def _crossing_violation(self, fn: FunctionInfo, expr: ast.expr) -> str | None:
        """A description of the provably non-exchangeable value `expr`
        carries, or None when it is (or cannot be proven not to be) in
        the picklable vocabulary."""
        prog = self.program
        if isinstance(expr, (ast.Constant, ast.JoinedStr)):
            return None
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for e in expr.elts:
                bad = self._crossing_violation(fn, e)
                if bad is not None:
                    return bad
            return None
        if isinstance(expr, ast.Dict):
            for e in (*expr.keys, *expr.values):
                if e is None:
                    continue
                bad = self._crossing_violation(fn, e)
                if bad is not None:
                    return bad
            return None
        if isinstance(expr, ast.Starred):
            return self._crossing_violation(fn, expr.value)
        if isinstance(expr, ast.Call):
            dotted = _dotted(expr.func)
            tail = dotted.split(".")[-1]
            if tail in _OK_CONVERTERS:
                return None
            return self._ctor_violation(fn, dotted)
        if isinstance(expr, ast.Name):
            bound = fn.local_types.get(expr.id)
            mod = prog.modules.get(fn.module)
            if mod is not None and expr.id in mod.module_locks:
                return f"module lock {expr.id!r} (threading primitives do not pickle)"
            if bound is None:
                return None
            if bound.endswith("()"):
                return self._ctor_violation(fn, bound[:-2])
            if bound.startswith("self.") and fn.cls is not None:
                return self._attr_violation(fn, bound.split(".")[1])
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" and fn.cls is not None:
                return self._attr_violation(fn, expr.attr)
            return None
        return None

    def _ctor_violation(self, fn: FunctionInfo, ctor: str) -> str | None:
        tail = ctor.split(".")[-1]
        root = ctor.split(".")[0]
        if tail in _OPEN_HANDLE_CTORS:
            return f"open file handle ({ctor}(...))"
        if root in ("jnp", "jax"):
            return f"jax value ({ctor}(...))"
        cls_q = self.program.class_of_ctor(fn.module, ctor)
        if cls_q is not None:
            simple = cls_q.split(".")[-1]
            if simple in _BANNED_CROSSING_CLASSES:
                return f"{simple} instance"
        elif tail in _BANNED_CROSSING_CLASSES:
            return f"{tail} instance"
        return None

    def _attr_violation(self, fn: FunctionInfo, attr: str) -> str | None:
        prog = self.program
        for cq in prog._mro(f"{fn.module}.{fn.cls}"):
            c = prog.classes.get(cq)
            if c is None:
                continue
            if attr in c.attr_locks:
                return f"threading {c.attr_locks[attr]} (self.{attr})"
            if attr in c.attr_types:
                ctor = c.attr_types[attr]
                got = self._ctor_violation(fn, ctor)
                if got is not None:
                    return got
                return None
        return None

    # -- HSL021: shared-file protocol --------------------------------------
    def _gated_modules(self) -> list[ModuleInfo]:
        out = []
        for m, mod in sorted(self.program.modules.items()):
            if m in self.domain_modules or ".fleet" in m or m.endswith("fleet"):
                out.append(mod)
        return out

    @staticmethod
    def _fn_uses_atomic_idiom(fn_node: ast.AST) -> bool:
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Call):
                tail = _dotted(sub.func).split(".")[-1]
                if tail in ("replace", "link", "mkstemp", "rename"):
                    return True
        return False

    @staticmethod
    def _open_mode(node: ast.Call) -> str | None:
        mode = None
        if (
            len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        return mode

    def shared_file_findings(self) -> list[Finding]:
        prog = self.program
        findings: list[Finding] = []
        for mod in self._gated_modules():
            if mod.path.endswith("file_utils.py"):
                # The sanctioned atomic-primitive module (HSL006's rule);
                # its O_EXCL lease still takes the reap check below.
                sanctioned_writes = True
            else:
                sanctioned_writes = False
            fns = list(mod.functions.values()) + [
                m for c in mod.classes.values() for m in c.methods.values()
            ]
            for fn in sorted(fns, key=lambda f: f.line):
                atomic_fn = self._fn_uses_atomic_idiom(fn.node)
                # Local path bindings: `path = exchange_dir / "x"` makes
                # a later `open(path, "w")` a shared-plane write even
                # though the call segment itself carries no marker.
                binds: dict[str, str] = {}
                for sub in ast.walk(fn.node):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Name):
                        txt = ast.get_source_segment(mod.source, sub.value) or ""
                        binds.setdefault(sub.targets[0].id, txt.lower())
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    dotted = _dotted(node.func)
                    tail = dotted.split(".")[-1]
                    seg = (ast.get_source_segment(mod.source, node) or "").lower()
                    for arg in node.args[:1]:
                        if isinstance(arg, ast.Name) and arg.id in binds:
                            seg += " " + binds[arg.id]
                    if isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id in binds:
                        seg += " " + binds[node.func.value.id]
                    is_excl = tail == "open" and dotted.startswith("os") and "o_excl" in seg
                    if is_excl:
                        self._check_lease_acquire(findings, mod, fn, node)
                        continue
                    if sanctioned_writes or atomic_fn:
                        continue
                    is_write = False
                    if tail in ("write_text", "write_bytes"):
                        is_write = True
                    elif tail == "open" and not dotted.startswith("os"):
                        mode = self._open_mode(node)
                        is_write = mode is not None and any(c in mode for c in "wax+")
                    elif tail == "open" and dotted.startswith("os"):
                        is_write = "o_wronly" in seg or "o_rdwr" in seg
                    if not is_write:
                        continue
                    if not any(mk in seg for mk in _SHARED_PATH_MARKERS):
                        continue
                    if _suppressed(mod, node.lineno, SHARED_FILE):
                        continue
                    findings.append(Finding(
                        mod.path, node.lineno, 0, SHARED_FILE,
                        f"bare write on a shared exchange/fleet path in "
                        f"{fn.qname} — another process can observe a torn "
                        f"entry; publish atomically (tempfile.mkstemp + fsync "
                        f"+ os.replace, or file_utils.write_json) or claim "
                        f"with O_CREAT|O_EXCL (shared-file protocol, "
                        f"docs/static_analysis.md)",
                        witness_paths=(mod.path,),
                    ))
        return findings

    def _check_lease_acquire(self, findings: list[Finding], mod: ModuleInfo,
                             fn: FunctionInfo, node: ast.Call) -> None:
        """An O_EXCL claim must reach (call graph, self included) a
        TTL-reap construct: a function comparing against a ttl/stale
        bound AND unlinking/renaming the lease — else a crashed holder
        wedges every later claimant forever."""
        prog, cg = self.program, self.callgraph
        candidates = {fn.qname} | cg.reachable(fn.qname)
        reap_via = None
        for q in sorted(candidates):
            f2 = prog.functions.get(q)
            if f2 is not None and self._is_reaper(f2):
                reap_via = cg.find_path(fn.qname, {q}) or [fn.qname, q]
                break
        self.lease_acquires.append({
            "fn": fn.qname, "line": node.lineno,
            "reap_via": list(reap_via) if reap_via else None,
        })
        if reap_via is None and not _suppressed(mod, node.lineno, SHARED_FILE):
            # The witness is the acquire's own reachable closure: the
            # reap this finding says is MISSING would live in one of
            # those files, so --changed keeps the finding when any
            # candidate module is edited.
            witness = tuple(dict.fromkeys(
                prog.modules[prog.functions[q].module].path
                for q in sorted(candidates) if q in prog.functions
            ))
            findings.append(Finding(
                mod.path, node.lineno, 0, SHARED_FILE,
                f"O_EXCL lease acquire in {fn.qname} has no reachable "
                f"TTL-reap/release path — a holder that dies here wedges "
                f"every later claimant forever; add a reap that judges the "
                f"creator-written epoch against a TTL and atomically clears "
                f"the lease (serve/fleet/lease.py is the pattern)",
                witness_paths=witness,
            ))

    @staticmethod
    def _is_reaper(fn: FunctionInfo) -> bool:
        has_ttl = False
        has_clear = False
        for sub in ast.walk(fn.node):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                ident = (getattr(sub, "id", "") or getattr(sub, "attr", "")).lower()
                if "ttl" in ident or "stale" in ident:
                    has_ttl = True
            if isinstance(sub, ast.Call):
                tail = _dotted(sub.func).split(".")[-1]
                if tail in ("unlink", "rename"):
                    has_clear = True
        return has_ttl and has_clear

    # -- HSL022: cross-boundary continuity ---------------------------------
    def continuity_findings(self) -> list[Finding]:
        prog = self.program
        findings: list[Finding] = []
        # (a) registry contract, both directions (the HSL012 shape):
        # every detected spawn target declared; every declared entry live.
        for site in self.boundary_sites:
            if site.kind not in ("submit", "spawn", "fleet_target", "mp_process"):
                continue
            if site.target is None or site.target in self.entry_points:
                continue
            fn = prog.functions.get(site.fn)
            mod = prog.modules.get(fn.module) if fn else None
            if mod is None or _suppressed(mod, site.line, CONTINUITY):
                continue
            findings.append(Finding(
                mod.path, site.line, 0, CONTINUITY,
                f"spawn target {site.target} ({site.kind} site in {site.fn}) "
                f"is not declared in SPAWN_ENTRY_POINTS — undeclared workers "
                f"escape the process-domain proofs (HSL019-022); declare it "
                f"with its kind in analysis/procdomain.py",
            ))
        for q, (kind, _) in sorted(self.entry_points.items()):
            if q in prog.functions:
                continue
            if not any(q.startswith(m + ".") for m in prog.modules):
                continue  # scanning a subset — the module is out of scope
            findings.append(Finding(
                next(iter(prog.modules.values())).path, 0, 0, CONTINUITY,
                f"stale SPAWN_ENTRY_POINTS entry: {q!r} ({kind}) names no "
                f"function in the analyzed program — fix the qname or delete "
                f"the entry",
            ))
        # (b) carrier plumbing per kind.
        for q, (kind, _) in sorted(self.live_entries.items()):
            fn = prog.functions[q]
            mod = prog.modules.get(fn.module)
            if mod is None or kind not in ("task", "service"):
                continue
            calls = {c.raw.split(".")[-1] for c in fn.calls}
            missing = []
            if "install_state" not in calls:
                missing.append("faults.install_state(shipped state) in the entry body")
            if kind == "task":
                mod_calls = set()
                for f2 in list(mod.functions.values()) + [
                    m for c in mod.classes.values() for m in c.methods.values()
                ]:
                    mod_calls.update(c.raw.split(".")[-1] for c in f2.calls)
                if "merge_observed" not in mod_calls:
                    missing.append("faults.merge_observed(...) on the join path")
                if "adopt_root" not in mod_calls:
                    missing.append("obs trace adopt_root(...) on the join path")
            if missing and not _suppressed(mod, fn.line, CONTINUITY):
                findings.append(Finding(
                    mod.path, fn.line, 0, CONTINUITY,
                    f"spawn entry point {q} ({kind}) breaks cross-boundary "
                    f"continuity: missing {'; '.join(missing)} — a worker "
                    f"spawned here silently loses injected faults or "
                    f"telemetry (docs/fault_tolerance.md)",
                ))
        # (c) worker telemetry vocabulary over the task domain.
        known_spans = _string_tuple_registry(prog, "KNOWN_WORKER_SPANS")
        known_counters = _string_tuple_registry(prog, "KNOWN_COUNTERS")
        known_events = _known_events(prog)
        for q in sorted(self.task_fns):
            fn = prog.functions.get(q)
            mod = prog.modules.get(fn.module) if fn else None
            if fn is None or mod is None:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                first = node.args[0]
                if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                    continue
                tail = _dotted(node.func).split(".")[-1]
                name = first.value
                bad = None
                if tail in ("span", "trace") and known_spans is not None \
                        and name not in known_spans:
                    bad = (f"worker span {name!r} is not declared in "
                           f"obs.trace KNOWN_WORKER_SPANS")
                elif tail == "increment" and known_counters is not None \
                        and name not in known_counters:
                    bad = (f"worker counter {name!r} is not declared in "
                           f"stats.KNOWN_COUNTERS")
                elif tail == "declare" and known_events is not None \
                        and name not in known_events:
                    bad = (f"worker event {name!r} is not declared in "
                           f"obs.events.KNOWN_EVENTS")
                if bad is None or _suppressed(mod, node.lineno, CONTINUITY):
                    continue
                witness = tuple(
                    prog.modules[prog.functions[w].module].path
                    for w in self.task_fns[q] if w in prog.functions
                )
                findings.append(Finding(
                    mod.path, node.lineno, 0, CONTINUITY,
                    f"{bad} — a worker process would emit telemetry the "
                    f"coordinator's registries don't know (witness: "
                    f"{' -> '.join(self.task_fns[q])}); declare the name or "
                    f"fix the typo",
                    witness_paths=witness,
                ))
        return findings

    # -- driver ------------------------------------------------------------
    def findings(self) -> list[Finding]:
        out = self.spawn_import_findings()
        out += self.exchange_typing_findings()
        out += self.shared_file_findings()
        out += self.continuity_findings()
        return out

    # -- report ------------------------------------------------------------
    def to_json(self) -> dict:
        """Stable JSON form (procdemo golden, --format json report): the
        inferred domain graph — entries, task closure with witness
        chains, domain modules with their import provenance, boundary
        sites, and the lease-acquire reap proofs."""
        return {
            "entry_points": {
                q: {"kind": kind, "live": q in self.program.functions}
                for q, (kind, _) in sorted(self.entry_points.items())
            },
            "task_functions": {
                q: list(chain) for q, chain in sorted(self.task_fns.items())
            },
            "domain_modules": {
                m: (via if via.startswith("hosts ") else f"imported by {via}")
                for m, (via, _) in sorted(self.domain_modules.items())
            },
            "boundary_sites": [
                {"fn": s.fn, "line": s.line, "kind": s.kind, "target": s.target}
                for s in sorted(
                    self.boundary_sites, key=lambda s: (s.fn, s.line, s.kind)
                )
            ],
            "lease_acquires": sorted(
                self.lease_acquires, key=lambda d: (d["fn"], d["line"])
            ),
        }
