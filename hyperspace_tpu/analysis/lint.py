"""Trace-safety / compat linter: AST rules for the jax bug classes that
actually bite this codebase.

Run over paths (files or directories) with::

    python -m hyperspace_tpu.analysis.lint hyperspace_tpu

Exit status is non-zero iff any finding is reported — the CI gate. Rules:

- **HSL001 fragile-jax-import** — importing jax symbols whose location
  changes across jax versions (`from jax import shard_map`, anything
  under `jax.experimental`) anywhere except the sanctioned
  ``hyperspace_tpu/compat.py``. The seed shipped exactly this bug: a
  bare ``from jax import shard_map`` produced 66 collection errors on
  jax 0.4.37. The compat module resolves such symbols once, with
  fallbacks; everything else imports from it.
- **HSL002 host-sync-in-jit** — forcing a traced value to a host Python
  value inside jitted/shard_mapped code: ``.item()``, ``.tolist()``,
  ``float()/int()/bool()`` on non-literals, ``np.asarray``/``np.array``,
  ``jax.device_get``. Under tracing these either fail
  (ConcretizationTypeError) or silently insert a blocking transfer.
- **HSL003 traced-control-flow** — Python ``if``/``while`` whose test
  reads a traced argument's VALUE inside jitted code. Shape/dtype
  attributes (``x.shape``, ``x.ndim``, ``x.dtype``, ``x.size``) are
  static and exempt; branching on the value itself needs ``lax.cond`` /
  ``jnp.where``.
- **HSL004 unhashable-static** — ``static_argnums``/``static_argnames``
  given a list/set/dict display. jit caches on static argument VALUES,
  which therefore must be hashable; the tuple spelling is required.
- **HSL005 unseeded-randomness** — module-level RNG calls
  (``np.random.rand`` etc., stdlib ``random.*``) and
  ``np.random.default_rng()`` with no seed. Unseeded randomness makes
  device results irreproducible across runs and shards; pass an explicit
  seed (``np.random.default_rng(0)``) or thread ``jax.random`` keys.
- **HSL007 wallclock-duration / undeclared-counter** — two observability
  hazards (docs/observability.md): (a) ``time.time()`` appearing in a
  subtraction — wall clock steps under NTP, so durations and TTL ages
  must use ``time.monotonic()``/``time.perf_counter()`` (persisted
  cross-process stamps are the legitimate exception; mark them
  ``# noqa: HSL007`` with a comment saying why); (b) ``stats.increment``
  with a constant counter name not declared in
  ``stats.KNOWN_COUNTERS`` — a typo'd name would raise at runtime (the
  declared-registry contract); the linter catches it before then. The
  declared set is read by parsing ``hyperspace_tpu/stats.py``'s AST, so
  the rule works in dependency-free CI.
- **HSL008 unlocked-global-mutation** — a module-level mutable container
  (dict/list/set/deque display or constructor) mutated from inside a
  function or method without a lock held (no enclosing ``with`` whose
  context expression names a lock). This is the bug class the serving
  plane's concurrency hardening removed (docs/serving.md): module
  globals that were safe under one caller become torn-eviction /
  lost-update races under N worker threads. Mutations at module level
  (import time, single-threaded) are exempt; so are the declared
  allowlist entries (:data:`HSL008_ALLOWED` — e.g. the obs no-op
  singleton plumbing, where a benign last-writer-wins is the design).
- **HSL006 metadata-write-bypass** — bare ``.write_text()`` /
  ``.write_bytes()`` / write-mode ``open()`` on metadata-plane paths
  (``_hyperspace_log`` entries, the ``latestStable`` pointer, the index
  manifest, ``v__=`` version dirs) anywhere except the sanctioned
  ``utils/file_utils.py``. A bare write is a torn write waiting for a
  crash: the metadata plane only stays crash-consistent because every
  commit goes through ``file_utils.write_json``/``atomic_write`` (temp
  file + fsync + atomic rename + dir fsync). The seed shipped exactly
  this bug in ``write_manifest`` (``Path.write_text``); this rule keeps
  it fixed.

Suppression: a finding on a line containing ``# noqa`` or
``# noqa: HSLxxx`` (matching rule id) is dropped.

This module is the *per-file* half of the analysis engine. The
whole-program rules — HSL009 lock-order inversion, HSL010 config-key
drift, HSL011 resource/exception safety, HSL012 fault-point coverage,
HSL013 lockset data races, HSL014 torn check-then-act, HSL015
jit-cache hygiene, HSL016 error-contract drift, HSL017 swallowed
crash/fault, HSL018 unwind safety, HSL019-022 the process-domain
invariants (spawn-import purity, exchange-surface typing, shared-file
protocol, cross-boundary continuity) — need the cross-module index
(analysis/program.py, callgraph.py, locks.py, effects.py, races.py,
raises.py, procdomain.py) and run from the unified
driver ``python -m hyperspace_tpu.analysis.check``, which parses each
file ONCE and feeds the same tree to this linter and to the program
index. All rules,
per-file and whole-program, are declared in :data:`RULES` — the one
registry the JSON report, the docs table, and the baseline key on.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import pathlib
import sys

FRAGILE_IMPORT = "HSL001"
HOST_SYNC = "HSL002"
TRACED_FLOW = "HSL003"
UNHASHABLE_STATIC = "HSL004"
UNSEEDED_RNG = "HSL005"
METADATA_WRITE = "HSL006"
WALLCLOCK_OR_UNDECLARED = "HSL007"
UNLOCKED_GLOBAL = "HSL008"

# Exit codes (`main` and analysis/check.py): CI must be able to tell "the
# tree has findings" from "the analyzer crashed".
EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL_ERROR = 2


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    """One registered rule: id, short slug, one-line summary, and where
    it runs ('file' = per-file AST walk here, 'program' = whole-program
    engine in check.py)."""

    rule: str
    slug: str
    summary: str
    scope: str = "file"


RULES: dict[str, RuleInfo] = {
    r.rule: r
    for r in (
        RuleInfo("HSL000", "unparseable", "file cannot be read or parsed"),
        RuleInfo("HSL001", "fragile-jax-import",
                 "version-fragile jax import outside the sanctioned compat.py"),
        RuleInfo("HSL002", "host-sync-in-jit",
                 "device->host sync (.item()/float()/np.asarray/...) inside traced code"),
        RuleInfo("HSL003", "traced-control-flow",
                 "Python if/while on a traced value inside jitted code"),
        RuleInfo("HSL004", "unhashable-static",
                 "static_argnums/static_argnames given an unhashable display"),
        RuleInfo("HSL005", "unseeded-randomness",
                 "global/unseeded RNG use — irreproducible across runs and shards"),
        RuleInfo("HSL006", "metadata-write-bypass",
                 "bare write to a metadata-plane path outside file_utils.py"),
        RuleInfo("HSL007", "wallclock-or-undeclared-counter",
                 "time.time() in a duration subtraction; undeclared stats counter name"),
        RuleInfo("HSL008", "unlocked-global-mutation",
                 "module-level container mutated in a function without a lock held"),
        RuleInfo("HSL009", "lock-order-inversion",
                 "cycle in the whole-program lock-acquisition graph", scope="program"),
        RuleInfo("HSL010", "config-key-drift",
                 "hyperspace.* config key not declared in config.KNOWN_KEYS (or declared and dead)",
                 scope="program"),
        RuleInfo("HSL011", "resource-safety",
                 "lock/span/file acquired outside with/try-finally on a raising path",
                 scope="program"),
        RuleInfo("HSL012", "fault-point-coverage",
                 "faults.KNOWN_POINTS and fault_point()/inject() call sites out of sync",
                 scope="program"),
        RuleInfo("HSL013", "lockset-race",
                 "shared state accessed under inconsistent locksets with a write in play",
                 scope="program"),
        RuleInfo("HSL014", "atomicity-violation",
                 "torn check-then-act: read under a lock, released, stale write-back re-acquiring it",
                 scope="program"),
        RuleInfo("HSL015", "jit-cache-hygiene",
                 "jit call site manufacturing a fresh cache key per call (recompile storm / executable leak)",
                 scope="program"),
        RuleInfo("HSL016", "error-contract-drift",
                 "statically observed escape not covered by exceptions.ERROR_CONTRACTS (or dead contract entry)",
                 scope="program"),
        RuleInfo("HSL017", "swallowed-crash",
                 "except clause absorbing CrashPoint/FaultError/everything without re-raise or signal",
                 scope="program"),
        RuleInfo("HSL018", "unwind-safety",
                 "fault point with no static path to a recovery construct; +=/-= pair unbalanced on unwind",
                 scope="program"),
        RuleInfo("HSL019", "spawn-import-purity",
                 "module reachable at worker start from a spawn entry point imports jax/pallas at module level",
                 scope="program"),
        RuleInfo("HSL020", "exchange-surface-typing",
                 "non-picklable/device value (ColumnTable, Span, lock, open handle, jax array) crosses a process boundary",
                 scope="program"),
        RuleInfo("HSL021", "shared-file-protocol",
                 "bare write on an exchange/fleet/lease path outside the atomic publish protocol; O_EXCL acquire with no reachable TTL reap",
                 scope="program"),
        RuleInfo("HSL022", "cross-boundary-continuity",
                 "spawn entry point missing fault/trace continuity plumbing; undeclared spawn target or worker telemetry name",
                 scope="program"),
        RuleInfo("HSL023", "traced-effect-purity",
                 "host effect (config/stats/event/lock/file/clock/materialization) reachable inside the jit trace-domain closure",
                 scope="program"),
        RuleInfo("HSL024", "signature-space-boundedness",
                 "jit key/static argument/pad width not derived from a declared bounded domain — recompile-storm risk",
                 scope="program"),
        RuleInfo("HSL025", "donation-aliasing-safety",
                 "zero-copy staged view mutated or donated without own_arrays; donated buffer referenced after the jitted call",
                 scope="program"),
        RuleInfo("HSL026", "kernel-fallback-ladder",
                 "Pallas engagement undeclared in ops.KNOWN_KERNELS or missing its exactness gate, permanent fallback, or device.kernel.* counters",
                 scope="program"),
        RuleInfo("HSL027", "durable-atomic-publish",
                 "durable write under a DURABLE_ROOTS plane does not reach the mkstemp + fsync + os.replace idiom — crash can surface a torn or zero-length file",
                 scope="program"),
        RuleInfo("HSL028", "torn-window-ordering",
                 "declared TORN_WINDOWS exactly-once protocol: two writes not statically ordered, or no KNOWN_POINTS fault point armed inside the window",
                 scope="program"),
        RuleInfo("HSL029", "replay-idempotence",
                 "durable file name on a REPLAY_ROOTS recovery/re-poll/takeover path derives from wall clock, pid, or RNG instead of cursor/log-id/generation values",
                 scope="program"),
        RuleInfo("HSL030", "snapshot-stamp-discipline",
                 "pinned-snapshot context reads the live version vector (get_latest_id/collection_log_versions/latest_log_id) instead of keying on the snapshot stamp",
                 scope="program"),
    )
}

# The one module allowed to touch version-fragile jax import paths.
SANCTIONED_COMPAT = "compat.py"
# The one module allowed to open metadata-plane paths for writing (it
# implements the atomic temp+fsync+rename primitives everything uses).
SANCTIONED_FILE_UTILS = "file_utils.py"

# Expression text that marks a write target as metadata-plane: the log
# dir and its pointer, version dirs, and the index manifest (both the
# literal names and the config/module constants they're spelled with).
_METADATA_PATH_MARKERS = (
    "_hyperspace_log",
    "lateststable",
    "hyperspace_log_dir",
    "latest_stable_log_name",
    "_index_manifest",
    "manifest_name",
    "data_version_prefix",
    "v__",
    "log_dir",
    "version_dir",
)

# HSL008 allowlist: (module basename, container name) pairs whose
# unlocked mutation is deliberate. The obs singletons' module state is
# written only through set_enabled/configure/reset — config-plane calls
# where last-writer-wins is the intended semantic, not a data race on
# the query path.
HSL008_ALLOWED = {
    ("trace.py", "NOOP"),
    ("trace.py", "_NOOP_TRACE"),
}

# Container constructors whose module-level result HSL008 tracks, and
# the method names that mutate such a container in place.
_HSL008_CTORS = {"dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter"}
_HSL008_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "clear",
    "remove", "discard",
}


def _declared_counters() -> "frozenset[str] | None":
    """Counter names declared in hyperspace_tpu/stats.py's
    KNOWN_COUNTERS tuple, extracted by AST parse (no import — the lint
    CI job runs without the package's dependencies installed). None when
    the file can't be located/parsed, which disables the check."""
    global _DECLARED_CACHE
    if _DECLARED_CACHE is not ...:
        return _DECLARED_CACHE
    _DECLARED_CACHE = None
    stats_path = pathlib.Path(__file__).resolve().parent.parent / "stats.py"
    try:
        tree = ast.parse(stats_path.read_text())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "KNOWN_COUNTERS":
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    names = [
                        e.value
                        for e in node.value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    ]
                    _DECLARED_CACHE = frozenset(names)
                    return _DECLARED_CACHE
    return None


_DECLARED_CACHE: "frozenset[str] | None | object" = ...


_JIT_NAMES = {"jit", "shard_map", "pmap"}
_HOST_SYNC_ATTRS = {"item", "tolist"}
_HOST_SYNC_CASTS = {"float", "int", "bool"}
_NP_SYNC_FNS = {"asarray", "array"}
_STATIC_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
_GLOBAL_RNG_FNS = {
    "rand", "randn", "randint", "random", "random_sample", "choice",
    "shuffle", "permutation", "normal", "uniform", "standard_normal", "seed",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    # Files (other than `path`) on the finding's witness chain — the
    # lock-order / escape / unwind / domain chains that PROVE the
    # finding. `--changed` mode keeps a finding when ANY witness file
    # changed, not just the primary location: editing a callee can
    # create a finding whose report line sits in an unchanged caller.
    # Not part of the baseline key (the message already pins the chain).
    witness_paths: tuple = ()

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a Name/Attribute chain ('' otherwise)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _static_params(decl: ast.AST, ordered_params: list[str]) -> set[str]:
    """Parameter names a jit declaration (decorator or wrapping call)
    marks static via static_argnames (strings) / static_argnums
    (positions into `ordered_params`)."""
    out: set[str] = set()
    for sub in ast.walk(decl):
        if not isinstance(sub, ast.Call):
            continue
        for kw in sub.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            values = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List, ast.Set))
                else [kw.value]
            )
            for v in values:
                if not isinstance(v, ast.Constant):
                    continue
                if kw.arg == "static_argnames" and isinstance(v.value, str):
                    out.add(v.value)
                elif kw.arg == "static_argnums" and isinstance(v.value, int):
                    if 0 <= v.value < len(ordered_params):
                        out.add(ordered_params[v.value])
    return out


def _mentions_jit(node: ast.AST) -> bool:
    """True when the (decorator / callee) expression references a
    jit-family transform anywhere: `jax.jit`, `functools.partial(jax.jit,
    ...)`, bare `jit`, `shard_map`, ..."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _JIT_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _JIT_NAMES:
            return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str, is_compat: bool, is_file_utils: bool = False):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.is_compat = is_compat
        self.is_file_utils = is_file_utils
        self.findings: list[Finding] = []
        # Names wrapped by a jit-family call somewhere in the module
        # (`return jax.jit(fn)` marks `fn` as traced code), and the call
        # nodes that wrapped them (their static_arg* declarations apply).
        self.jit_wrapped: set[str] = set()
        self.static_decls: dict[str, list[ast.AST]] = {}
        # Stack of (in_jit_context, param_names) per function scope.
        self._fn_stack: list[tuple[bool, frozenset]] = []
        # HSL008 state: module-level mutable container names, and how
        # many lock-holding `with` blocks enclose the current node.
        self.module_containers: set[str] = set()
        self._lock_depth = 0

    def collect_module_containers(self, tree: ast.Module) -> None:
        """Names assigned a mutable container display/constructor at
        module level (HSL008 candidates). Only simple top-level
        assignments count — a container built inside a function is local
        state, and attribute targets belong to lock-owning objects."""
        for node in tree.body:
            targets: list[ast.expr] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            is_container = isinstance(
                value, (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp, ast.SetComp)
            ) or (
                isinstance(value, ast.Call)
                and _dotted(value.func).split(".")[-1] in _HSL008_CTORS
            )
            if not is_container:
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    basename = pathlib.PurePath(self.path).name
                    if (basename, tgt.id) not in HSL008_ALLOWED:
                        self.module_containers.add(tgt.id)

    # -- bookkeeping ---------------------------------------------------------

    def collect_jit_wrapped(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and node.args
                and isinstance(node.args[0], ast.Name)
                and _mentions_jit(node.func)
            ):
                self.jit_wrapped.add(node.args[0].id)
                self.static_decls.setdefault(node.args[0].id, []).append(node)

    def _report(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        text = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        if "# noqa" in text:
            tail = text.split("# noqa", 1)[1]
            if not tail.strip().startswith(":") or rule in tail:
                return
        self.findings.append(
            Finding(self.path, line, getattr(node, "col_offset", 0), rule, message)
        )

    def _in_jit(self) -> bool:
        return any(flag for flag, _ in self._fn_stack)

    def _jit_params(self) -> set[str]:
        out: set[str] = set()
        for flag, params in self._fn_stack:
            if flag:
                out |= params
        return out

    # -- HSL001: fragile imports ---------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        if not self.is_compat:
            for alias in node.names:
                if alias.name == "jax.experimental" or alias.name.startswith("jax.experimental."):
                    self._report(
                        node, FRAGILE_IMPORT,
                        f"import of {alias.name!r} outside compat.py — jax moves "
                        f"experimental symbols between versions; resolve it in "
                        f"hyperspace_tpu/compat.py and import from there",
                    )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if not self.is_compat and node.module:
            if node.module == "jax":
                fragile = [a.name for a in node.names if a.name in ("shard_map", "enable_x64")]
                for name in fragile:
                    self._report(
                        node, FRAGILE_IMPORT,
                        f"'from jax import {name}' is version-fragile (moved "
                        f"between jax releases; broke collection on jax "
                        f"0.4.37) — import it from hyperspace_tpu.compat",
                    )
            elif node.module == "jax.experimental" or node.module.startswith("jax.experimental."):
                self._report(
                    node, FRAGILE_IMPORT,
                    f"import from {node.module!r} outside compat.py — resolve "
                    f"experimental symbols in hyperspace_tpu/compat.py",
                )
        self.generic_visit(node)

    # -- function scopes -----------------------------------------------------

    def _visit_fn(self, node) -> None:
        in_jit = (
            any(_mentions_jit(d) for d in node.decorator_list)
            or node.name in self.jit_wrapped
            or self._in_jit()  # nested defs inherit the traced context
        )
        ordered = [*node.args.posonlyargs, *node.args.args]
        params = {
            a.arg
            for a in [
                *ordered, *node.args.kwonlyargs,
                *( [node.args.vararg] if node.args.vararg else [] ),
                *( [node.args.kwarg] if node.args.kwarg else [] ),
            ]
        }
        # Parameters declared static (static_argnums/static_argnames on
        # the jit decorator or wrapping call) hold ordinary Python values
        # — control flow on them is fine.
        for decl in [*node.decorator_list, *self.static_decls.get(node.name, [])]:
            params -= _static_params(decl, [a.arg for a in ordered])
        self._fn_stack.append((in_jit, frozenset(params)))
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- HSL002 / HSL004 / HSL005: calls -------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)

        # HSL004: static_argnums/static_argnames must be hashable (tuple).
        for kw in node.keywords:
            if kw.arg in ("static_argnums", "static_argnames") and isinstance(
                kw.value, (ast.List, ast.Set, ast.Dict)
            ):
                self._report(
                    node, UNHASHABLE_STATIC,
                    f"{kw.arg} given a {type(kw.value).__name__.lower()} "
                    f"display; jit hashes static argument POSITIONS and "
                    f"values — use a tuple",
                )

        # HSL005: module-level RNG state.
        parts = dotted.split(".")
        if len(parts) >= 2 and parts[-2] == "random" and parts[0] in ("np", "numpy", "jax"):
            if parts[0] != "jax" and parts[-1] in _GLOBAL_RNG_FNS:
                self._report(
                    node, UNSEEDED_RNG,
                    f"{dotted}() uses numpy's global RNG — results are not "
                    f"reproducible across runs/shards; use "
                    f"np.random.default_rng(seed)",
                )
            elif parts[-1] == "default_rng" and not node.args and not node.keywords:
                self._report(
                    node, UNSEEDED_RNG,
                    "np.random.default_rng() without a seed is entropy-seeded "
                    "— pass an explicit seed for reproducible builds",
                )
        elif len(parts) == 2 and parts[0] == "random" and parts[1] in (
            _GLOBAL_RNG_FNS | {"gauss", "sample", "randrange"}
        ):
            self._report(
                node, UNSEEDED_RNG,
                f"stdlib {dotted}() draws from global, unseeded state — "
                f"use a seeded np.random.default_rng",
            )

        # HSL006: bare writes to metadata-plane paths.
        self._check_metadata_write(node)

        # HSL007(b): stats.increment with an undeclared constant name.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "increment"
            and "stats" in _dotted(node.func.value).lower()
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            declared = _declared_counters()
            if declared is not None and node.args[0].value not in declared:
                self._report(
                    node, WALLCLOCK_OR_UNDECLARED,
                    f"counter {node.args[0].value!r} is not declared in "
                    f"stats.KNOWN_COUNTERS — undeclared names raise at "
                    f"runtime (the declared-registry contract); fix the "
                    f"typo or declare it",
                )

        # HSL008: in-place mutation of a module-level container from a
        # function without a lock held.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HSL008_MUTATORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self.module_containers
        ):
            self._check_global_mutation(node, node.func.value.id, f".{node.func.attr}()")

        # HSL002: host sync inside traced code.
        if self._in_jit():
            if isinstance(node.func, ast.Attribute) and node.func.attr in _HOST_SYNC_ATTRS:
                self._report(
                    node, HOST_SYNC,
                    f".{node.func.attr}() forces a device->host transfer and "
                    f"fails under tracing — return the array and read it "
                    f"outside the jitted function",
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in _HOST_SYNC_CASTS
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                self._report(
                    node, HOST_SYNC,
                    f"{node.func.id}() on a traced value raises "
                    f"ConcretizationTypeError inside jit — keep it an array "
                    f"(jnp.float32(...) etc.) or hoist the cast to the host",
                )
            elif parts[-1] in _NP_SYNC_FNS and parts[0] in ("np", "numpy"):
                self._report(
                    node, HOST_SYNC,
                    f"{dotted}() materializes a traced value on host inside "
                    f"jit — use jnp equivalents",
                )
            elif dotted in ("jax.device_get",):
                self._report(
                    node, HOST_SYNC,
                    "jax.device_get inside jitted code blocks on a transfer "
                    "that tracing cannot represent",
                )
        self.generic_visit(node)

    # -- HSL006: bare metadata-plane writes ------------------------------------

    def _check_metadata_write(self, node: ast.Call) -> None:
        """Flag `<expr>.write_text/.write_bytes(...)` and write-mode
        `open(...)` whose expression text names a metadata-plane path
        (operation-log entries, latestStable, the index manifest,
        version dirs) outside file_utils.py — such writes are torn on
        crash; the atomic primitives exist precisely so they can't be."""
        if self.is_file_utils:
            return
        is_write = (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("write_text", "write_bytes")
        )
        if not is_write and isinstance(node.func, ast.Name) and node.func.id == "open":
            mode = None
            if (
                len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                mode = node.args[1].value
            for kw in node.keywords:
                if (
                    kw.arg == "mode"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    mode = kw.value.value
            is_write = mode is not None and any(c in mode for c in "wax+")
        if not is_write:
            return
        seg = (ast.get_source_segment(self.source, node) or "").lower()
        if any(m in seg for m in _METADATA_PATH_MARKERS):
            self._report(
                node, METADATA_WRITE,
                "bare write to a metadata-plane path (operation log / "
                "latestStable / manifest / version dir) — a crash mid-write "
                "tears it; route through file_utils.write_json/atomic_write "
                "(temp file + fsync + atomic rename + dir fsync)",
            )

    # -- HSL008: unlocked module-global container mutation ---------------------

    def _check_global_mutation(self, node: ast.AST, name: str, how: str) -> None:
        if not self._fn_stack:
            return  # module level runs once at import, single-threaded
        if self._lock_depth > 0:
            return
        self._report(
            node, UNLOCKED_GLOBAL,
            f"module-level container {name!r} mutated ({how}) outside a "
            f"lock — safe single-threaded, a lost-update/torn-eviction "
            f"race under the concurrent serving plane (docs/serving.md); "
            f"guard it with a module lock (`with _lock:`) or move it into "
            f"a lock-guarded class",
        )

    def _subscript_base(self, tgt: ast.expr) -> str | None:
        """The bare module-container name a Subscript target indexes, if
        any (`NAME[k] = v` / `del NAME[k]` / `NAME[k] += v`)."""
        if isinstance(tgt, ast.Subscript) and isinstance(tgt.value, ast.Name):
            if tgt.value.id in self.module_containers:
                return tgt.value.id
        return None

    def visit_With(self, node: ast.With) -> None:
        held = any(
            "lock" in (ast.get_source_segment(self.source, item.context_expr) or "").lower()
            for item in node.items
        )
        if held:
            self._lock_depth += 1
        try:
            self.generic_visit(node)
        finally:
            if held:
                self._lock_depth -= 1

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            base = self._subscript_base(tgt)
            if base is not None:
                self._check_global_mutation(node, base, "[...] = ")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        base = self._subscript_base(node.target)
        if base is not None:
            self._check_global_mutation(node, base, "[...] op= ")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            base = self._subscript_base(tgt)
            if base is not None:
                self._check_global_mutation(node, base, "del [...]")
        self.generic_visit(node)

    # -- HSL007(a): wall-clock duration measurement ----------------------------

    def visit_BinOp(self, node: ast.BinOp) -> None:
        """`time.time() - x` / `x - time.time()` measures a duration (or
        a TTL age) with a steppable clock: an NTP adjustment makes it
        negative or wildly large. Durations want time.monotonic() /
        time.perf_counter(); persisted cross-process stamps are the one
        legitimate wall-clock use — annotate those `# noqa: HSL007`."""
        if isinstance(node.op, ast.Sub):
            for side in (node.left, node.right):
                if isinstance(side, ast.Call) and _dotted(side.func) == "time.time":
                    self._report(
                        node, WALLCLOCK_OR_UNDECLARED,
                        "time.time() in a subtraction — wall clock steps "
                        "under NTP; measure durations/TTL ages with "
                        "time.monotonic() or time.perf_counter() (persisted "
                        "cross-process stamps may stay wall-clock with a "
                        "negative-age guard and `# noqa: HSL007`)",
                    )
                    break
        self.generic_visit(node)

    # -- HSL003: traced-value control flow ------------------------------------

    def _check_branch(self, node, kind: str) -> None:
        if self._in_jit():
            tainted = self._traced_value_names(node.test)
            if tainted:
                self._report(
                    node, TRACED_FLOW,
                    f"Python {kind} on traced value(s) {sorted(tainted)} "
                    f"inside jitted code — branch decisions must use "
                    f"lax.cond/lax.while_loop/jnp.where (shape/dtype "
                    f"attributes are static and fine)",
                )
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")

    def _traced_value_names(self, test: ast.AST) -> set[str]:
        """Parameter names whose runtime VALUE the test reads. A name
        consumed only through static attributes (x.shape, x.ndim, ...)
        or len() does not count."""
        params = self._jit_params()
        if not params:
            return set()
        static_ids: set[int] = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_SHAPE_ATTRS:
                for inner in ast.walk(sub.value):
                    if isinstance(inner, ast.Name):
                        static_ids.add(id(inner))
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in ("len", "isinstance", "getattr", "hasattr")
            ):
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Name):
                        static_ids.add(id(inner))
        return {
            sub.id
            for sub in ast.walk(test)
            if isinstance(sub, ast.Name)
            and sub.id in params
            and id(sub) not in static_ids
        }


# -- driver ------------------------------------------------------------------

def lint_source(source: str, path: str = "<string>", tree: ast.Module | None = None) -> list[Finding]:
    """Lint one source text; `path` only labels findings (a basename of
    compat.py marks the sanctioned module). Pass `tree` to reuse an
    existing parse — the unified check driver parses each file exactly
    once and feeds the same AST to this linter and the program index."""
    if tree is None:
        tree = ast.parse(source, filename=path)
    name = pathlib.PurePath(path).name
    linter = _Linter(
        path, source, name == SANCTIONED_COMPAT, is_file_utils=name == SANCTIONED_FILE_UTILS
    )
    linter.collect_jit_wrapped(tree)
    linter.collect_module_containers(tree)
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.col))


def lint_paths(paths: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        root = pathlib.Path(p)
        files = sorted(root.rglob("*.py")) if root.is_dir() else [root]
        for f in files:
            try:
                src = f.read_text()
            except OSError as e:
                findings.append(Finding(str(f), 0, 0, "HSL000", f"unreadable: {e}"))
                continue
            try:
                findings.extend(lint_source(src, str(f)))
            except SyntaxError as e:
                findings.append(
                    Finding(str(f), e.lineno or 0, e.offset or 0, "HSL000",
                            f"syntax error: {e.msg}")
                )
    return findings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.analysis.lint",
        description="Trace-safety / jax-compat / observability linter (rules HSL001-HSL007).",
    )
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument(
        "-q", "--quiet", action="store_true", help="suppress the summary line"
    )
    args = ap.parse_args(argv)
    # Unambiguous exit codes: 0 = clean, 1 = findings, 2 = the linter
    # itself crashed (an unreadable/unparseable TARGET is a finding —
    # HSL000 — not a crash).
    try:
        findings = lint_paths(args.paths)
        for f in findings:
            print(f)
        if not args.quiet:
            print(f"{len(findings)} finding(s)", file=sys.stderr)
    except Exception as e:  # pragma: no cover - exercised via unit test stub
        print(f"internal error: {type(e).__name__}: {e}", file=sys.stderr)
        return EXIT_INTERNAL_ERROR
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
