"""Trace-domain analysis: which code runs inside a TRACE (HSL023-026).

The device plane stacks everything on conventions that, until this
layer, were enforced only by review and by runtime observation: jitted
bodies are host-effect-free, jit cache keys come from a bounded
signature space (obs/runtime.py's ``jit.recompile_storm`` event merely
*observes* violations after the fact), zero-copy staged arrays
(execution/staging.py's writeable=False => identity-stable contract)
are never mutated or donated, and every Pallas engagement sits behind a
provable-exactness gate with a permanent per-shape fallback. This
module is the device-plane dual of :mod:`procdomain`: instead of
inferring which code runs in which *process*, it infers which code runs
inside a *trace*, then turns each convention into a checked rule.

- **The trace-domain inference.** A *trace entry* is any function
  object handed to a tracing transform: ``compat.jit`` (call form
  ``jit(fn, key=...)`` inside a factory, decorator form ``@jit`` /
  ``@functools.partial(jit, static_argnames=...)``), ``shard_map``
  bodies (same two forms), and Pallas kernel bodies (the first argument
  of a ``pallas_call``). Entries come in two shapes the engine treats
  uniformly: *program functions* (module-level / method defs with their
  own FunctionInfo summaries) and *nested defs* (the ``run``/``kernel``
  closures manufactured inside lru_cache factories — program.py folds
  their call sites into the enclosing function's summary, so the
  nested body is re-walked at AST level and its calls resolved with the
  enclosing function as import/type context). The *trace domain* is the
  dispatch-augmented call-graph closure of every entry, with witness
  chains recorded exactly like procdomain's task closure.

- **HSL023 traced-effect purity.** Nothing in the trace domain may
  touch the host: no ``conf.get``/``conf.set``, no ``stats.increment``,
  no event ``emit``, no lock acquire, no file IO, no ``fault_point``,
  no wall clock, and no host materialization (``.item()``/``.tolist()``,
  ``float()``/``int()``/``bool()`` on non-literals, ``np.asarray``,
  ``jax.device_get``). This is the whole-program upgrade of the
  per-file HSL002/HSL003 checks: those only see a lexically-jitted
  body; this rule follows the closure, so an effect buried two calls
  deep inside a traced helper is found with an entry -> callee witness
  chain.

- **HSL024 signature-space boundedness.** The static proof of
  recompile-storm freedom that HSL015 and the runtime storm event only
  approximate. Three legs: (1) every ``key=`` at a jit site must be a
  string literal (per-call keys defeat the storm detector's grouping);
  (2) every call-form jit must be manufactured inside a bounded cache —
  an ``lru_cache`` factory with a real ``maxsize`` or the HSL015
  memo-container idiom — so the set of live jit callables is finite;
  (3) every static argument name must be declared in
  ``compat.KNOWN_STATIC_DOMAINS`` (or be a parameter of a bounded
  factory, whose memo key already bounds it), and every
  shape-determining pad width must derive from a tile-rounding helper
  (a function returning ``//``/``<<`` arithmetic) rather than a raw
  data-dependent shape. The registry is AST-extracted like
  ``SPAWN_ENTRY_POINTS`` — fixture packages declare their own.

- **HSL025 donation/aliasing safety.** The exact precondition the
  ROADMAP's donated-buffer plans need. A writeable=False staged view
  (a ``stage_column(...)`` result or a ``from_arrow(...,
  zero_copy_ok=True)`` table) may never be mutated in place — callers
  must go through ``ColumnTable.own_arrays`` first — and may never be
  donated to a jitted call; a donated buffer must not be referenced on
  any path after the call that donated it. The report carries a
  donation proof: every staged-view producer, every donation site
  (empty today — that IS the proof), and the ``own_arrays`` ownership
  gateways with call-chain witnesses.

- **HSL026 kernel fallback-ladder completeness.** Every Pallas
  engagement must be declared in ``ops.KNOWN_KERNELS`` (mirroring
  ``faults.KNOWN_POINTS``, both directions: undeclared engagements and
  stale registry entries are findings), and its *engagement closure*
  (the kernel factory plus its same-module transitive callers) must
  statically contain the full ladder: an exactness/eligibility gate (a
  comparison against an uppercase module constant), a permanent
  per-shape fallback (a ``*bad*`` set consulted with ``in``/``not in``
  and grown with ``.add`` under a lock), and both a success and a
  fallback ``device.kernel.*`` counter, each declared in
  ``stats.KNOWN_COUNTERS``. The report carries a per-kernel ladder
  proof with the caller-chain witness from the public op down to the
  factory.

Everything here is stdlib-``ast`` only and never imports analyzed code,
same as the rest of the engine (docs/static_analysis.md).
"""

from __future__ import annotations

import ast
import dataclasses

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.lint import (
    _HOST_SYNC_ATTRS,
    _HOST_SYNC_CASTS,
    _NP_SYNC_FNS,
    Finding,
    _dotted,
)
from hyperspace_tpu.analysis.procdomain import _string_tuple_registry, _suppressed
from hyperspace_tpu.analysis.program import FunctionInfo, ModuleInfo, Program

TRACED_EFFECT = "HSL023"
SIGNATURE_SPACE = "HSL024"
DONATION_SAFETY = "HSL025"
KERNEL_LADDER = "HSL026"

#: Call tails that enter a trace when handed a function object.
_TRANSFORMS = ("jit", "shard_map")

#: Wall-clock reads: meaningless inside a trace (they run once, at
#: trace time, and bake a constant into the compiled program).
_WALLCLOCK = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
}

#: File-IO call tails (HSL023): a traced body must never touch a file.
_FILE_IO_TAILS = {"write_text", "write_bytes", "read_text", "read_bytes"}


def _uppercase_const(name: str) -> bool:
    """Module-constant naming convention: _MAX_PALLAS_K, _RB_TILE, ..."""
    body = name.lstrip("_")
    return bool(body) and body == body.upper() and any(c.isalpha() for c in body)


def declared_static_domains(program: Program) -> set[str] | None:
    """Keys of every scanned module's top-level ``KNOWN_STATIC_DOMAINS``
    dict literal (the real registry lives in compat.py; fixture packages
    and corpus files declare their own), or None when no module declares
    one — the checks that read it disarm, so a corpus file scanned alone
    does not report every static argument undeclared."""
    out: set[str] | None = None
    for mod in program.modules.values():
        for node in mod.tree.body:
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not (isinstance(target, ast.Name) and target.id == "KNOWN_STATIC_DOMAINS"):
                continue
            if isinstance(value, ast.Dict):
                out = out or set()
                out.update(
                    k.value for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                )
    return out


def _registry_site(program: Program, name: str) -> tuple[ModuleInfo, int] | None:
    """(module, line) of the first top-level assignment declaring `name`."""
    for _, mod in sorted(program.modules.items()):
        for node in mod.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if isinstance(target, ast.Name) and target.id == name:
                return mod, node.lineno
    return None


@dataclasses.dataclass
class TraceEntry:
    """One function object handed to a tracing transform."""

    traced: str                  # qname, or `<host>.<locals>.<name>` for nested defs
    kind: str                    # "jit" | "shard_map" | "pallas_kernel"
    form: str                    # "call" | "decorator"
    host: str                    # enclosing program function qname (the site)
    line: int                    # site line
    key: str | None = None      # constant key= when present
    key_literal: bool = True    # False when key= is a non-constant expression
    static_names: tuple[str, ...] = ()
    donate_nums: tuple[int, ...] = ()
    donate_names: tuple[str, ...] = ()
    node: ast.AST | None = None  # nested def body when not a program function

    @property
    def donates(self) -> bool:
        return bool(self.donate_nums or self.donate_names)


def _transform_kind(dec: ast.AST) -> tuple[str, ast.Call | None] | None:
    """Classify a decorator (or decorator-shaped expression): returns
    (kind, kwargs-bearing Call or None) for jit/shard_map decorators in
    any of their three spellings: bare ``@jit``, ``@jit(...)``, and
    ``@functools.partial(jit, ...)``."""
    if isinstance(dec, (ast.Name, ast.Attribute)):
        tail = _dotted(dec).rsplit(".", 1)[-1]
        if tail in _TRANSFORMS:
            return tail, None
        return None
    if isinstance(dec, ast.Call):
        ftail = _dotted(dec.func).rsplit(".", 1)[-1]
        if ftail in _TRANSFORMS:
            return ftail, dec
        if ftail == "partial" and dec.args:
            atail = _dotted(dec.args[0]).rsplit(".", 1)[-1]
            if atail in _TRANSFORMS:
                return atail, dec
    return None


def _jit_kwargs(call: ast.Call | None) -> dict:
    """Extract the signature-shaping kwargs of a jit/shard_map call:
    key=, static_argnames/argnums, donate_argnums/argnames."""
    out = {
        "key": None, "key_literal": True, "static_names": (),
        "donate_nums": (), "donate_names": (),
    }
    if call is None:
        return out
    statics: list[str] = []
    dnums: list[int] = []
    dnames: list[str] = []
    for kw in call.keywords:
        values = (
            kw.value.elts
            if isinstance(kw.value, (ast.Tuple, ast.List, ast.Set))
            else [kw.value]
        )
        if kw.arg == "key":
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                out["key"] = kw.value.value
            else:
                out["key_literal"] = False
        elif kw.arg == "static_argnames":
            statics += [v.value for v in values
                        if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        elif kw.arg == "static_argnums":
            statics += [str(v.value) for v in values
                        if isinstance(v, ast.Constant) and isinstance(v.value, int)]
        elif kw.arg == "donate_argnums":
            dnums += [v.value for v in values
                      if isinstance(v, ast.Constant) and isinstance(v.value, int)]
        elif kw.arg == "donate_argnames":
            dnames += [v.value for v in values
                       if isinstance(v, ast.Constant) and isinstance(v.value, str)]
    out["static_names"] = tuple(statics)
    out["donate_nums"] = tuple(dnums)
    out["donate_names"] = tuple(dnames)
    return out


def _lru_bound(fn_node: ast.AST) -> str | None:
    """"bounded" / "unbounded" when fn is lru_cache-decorated (explicit
    ``maxsize=None`` is the unbounded spelling; the 128 default and any
    integer are bounded), None when it is not a cache factory at all."""
    for dec in getattr(fn_node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            if _dotted(dec).rsplit(".", 1)[-1] == "lru_cache":
                return "bounded"
            continue
        if _dotted(dec.func).rsplit(".", 1)[-1] != "lru_cache":
            continue
        for kw in dec.keywords:
            if kw.arg == "maxsize":
                if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                    return "unbounded"
                return "bounded"
        if dec.args:
            first = dec.args[0]
            if isinstance(first, ast.Constant) and first.value is None:
                return "unbounded"
        return "bounded"
    return None


def _sub_root(node: ast.AST) -> str | None:
    """Base Name of a Subscript/Attribute store-target chain."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class TraceDomains:
    """Infer the trace domain and check HSL023-026 over it.

    Same engine contract as :class:`procdomain.ProcessDomains`: built
    from the program summaries and call graph, never importing analyzed
    code; ``findings()`` returns the rule violations and ``to_json()``
    the inferred graph (golden-tested for the jitdemo fixture and
    shipped in the check report's ``trace_domains`` section).
    """

    def __init__(self, program: Program, callgraph: CallGraph, raises=None):
        self.program = program
        self.callgraph = callgraph
        self.raises = raises

        self.entries: list[TraceEntry] = []
        #: pseudo-qname -> (nested def node, enclosing FunctionInfo)
        self.entry_bodies: dict[str, tuple[ast.AST, FunctionInfo]] = {}
        #: trace-domain program functions: qname -> witness chain
        self.trace_fns: dict[str, tuple[str, ...]] = {}
        self.trace_calls_total = 0
        self.trace_calls_unresolved = 0

        self.static_domains = declared_static_domains(program)
        self.known_kernels = _string_tuple_registry(program, "KNOWN_KERNELS")
        self.known_counters = _string_tuple_registry(program, "KNOWN_COUNTERS")

        self._find_entries()
        self._build_closure()
        self._kernel_ladders = self._build_ladders()
        self._donation = None  # built by donation_findings()
        self._findings: list[Finding] | None = None

    # -- entry detection -------------------------------------------------------

    def _find_entries(self) -> None:
        prog, cg = self.program, self.callgraph
        seen: set[tuple[str, str, int]] = set()

        def add(entry: TraceEntry) -> None:
            dedup = (entry.traced, entry.kind, entry.line)
            if dedup not in seen:
                seen.add(dedup)
                self.entries.append(entry)

        for q in sorted(prog.functions):
            fn = prog.functions[q]
            nested: dict[str, ast.AST] = {}
            for sub in ast.walk(fn.node):
                if (
                    isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub is not fn.node
                ):
                    nested.setdefault(sub.name, sub)

            # Decorator form on the program function itself.
            for dec in fn.node.decorator_list:
                got = _transform_kind(dec)
                if got is None:
                    continue
                kind, call = got
                kw = _jit_kwargs(call)
                add(TraceEntry(
                    traced=q, kind=kind, form="decorator", host=q,
                    line=fn.node.lineno, node=None, **kw,
                ))

            # Decorator form on nested defs (shard_map bodies inside
            # factories: `@functools.partial(shard_map, mesh=...)`).
            for name in sorted(nested):
                nd = nested[name]
                for dec in getattr(nd, "decorator_list", []):
                    got = _transform_kind(dec)
                    if got is None:
                        continue
                    kind, call = got
                    kw = _jit_kwargs(call)
                    add(TraceEntry(
                        traced=f"{q}.<locals>.{name}", kind=kind,
                        form="decorator", host=q, line=nd.lineno, node=nd, **kw,
                    ))

            # Call form: jit(fn, key=...), shard_map(fn, ...),
            # pl.pallas_call(kernel, ...).
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                tail = _dotted(node.func).rsplit(".", 1)[-1]
                if tail not in _TRANSFORMS and tail != "pallas_call":
                    continue
                raw = _dotted(node.args[0])
                if not raw:
                    continue
                kind = "pallas_kernel" if tail == "pallas_call" else tail
                kw = _jit_kwargs(node)
                if raw in nested:
                    add(TraceEntry(
                        traced=f"{q}.<locals>.{raw}", kind=kind, form="call",
                        host=q, line=node.lineno, node=nested[raw], **kw,
                    ))
                else:
                    got = cg.resolve_call(fn, raw)
                    if got is not None and got in prog.functions:
                        add(TraceEntry(
                            traced=got, kind=kind, form="call", host=q,
                            line=node.lineno, node=None, **kw,
                        ))

        for e in self.entries:
            if e.node is not None and e.traced not in self.entry_bodies:
                self.entry_bodies[e.traced] = (e.node, prog.functions[e.host])

    # -- closure ---------------------------------------------------------------

    def _dispatch(self, callee: str) -> tuple[str, ...]:
        if self.raises is not None:
            return self.raises.dispatch_targets(callee)
        return (callee,)

    def _resolve_traced(self, fn: FunctionInfo, raw: str) -> str | None:
        """resolve_call, minus the unique-method-name fallback for
        ungrounded receivers. Traced bodies call mostly jax APIs
        (``jax.lax.scan``, ``x.sum()``) whose names collide with program
        methods (``Dataset.scan``, ``Histogram.sum``); accepting the
        name-only fallback would pull host code into the trace domain
        and manufacture false purity findings. Rejections are counted in
        the unresolved ratio — the honest record of the blind spot."""
        got = self.callgraph.resolve_call(fn, raw)
        if got is None:
            return None
        parts = raw.split(".")
        if len(parts) == 1 or "()." in raw or parts[0] in ("self", "super"):
            return got
        prog = self.program
        root = parts[0]
        target = prog.resolve_symbol(fn.module, root, fn=fn)
        if target is not None and (
            target in prog.functions
            or target in prog.classes
            or any(
                m == target or m.startswith(target + ".")
                for m in prog.modules
            )
        ):
            return got
        src = fn.local_types.get(root)
        mod = prog.modules.get(fn.module)
        if src is None and mod is not None:
            src = mod.var_types.get(root)
        if src is not None:
            if src.endswith("()") and prog.class_of_ctor(
                fn.module, src[:-2], fn=fn
            ):
                return got
            if src.startswith("self."):
                return got
        return None

    def _build_closure(self) -> None:
        prog, cg = self.program, self.callgraph
        stack: list[str] = []

        for e in self.entries:
            if e.node is None and e.traced not in self.trace_fns:
                self.trace_fns[e.traced] = (e.traced,)
                stack.append(e.traced)

        # Nested entry bodies: program.py folds their calls into the
        # enclosing factory's summary, but the factory itself is host
        # code — so the nested body is re-walked here and its calls
        # resolved with the factory as context (the factory's imports
        # and local types are exactly the names the body closes over).
        for traced in sorted(self.entry_bodies):
            node, host_fn = self.entry_bodies[traced]
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    raw = _dotted(sub.func)
                    if not raw:
                        continue
                    self.trace_calls_total += 1
                    got = self._resolve_traced(host_fn, raw)
                    if got is None:
                        self.trace_calls_unresolved += 1
                        continue
                    for t in self._dispatch(got):
                        if t in prog.functions and t not in self.trace_fns:
                            self.trace_fns[t] = (traced, t)
                            stack.append(t)

        while stack:
            q = stack.pop()
            fn = prog.functions.get(q)
            if fn is None:
                continue
            for call in fn.calls:
                self.trace_calls_total += 1
                callee = self._resolve_traced(fn, call.raw)
                if callee is None:
                    self.trace_calls_unresolved += 1
                    continue
                for t in self._dispatch(callee):
                    if t in prog.functions and t not in self.trace_fns:
                        self.trace_fns[t] = (*self.trace_fns[q], t)
                        stack.append(t)

    def unresolved_ratio(self) -> float:
        if not self.trace_calls_total:
            return 0.0
        return round(self.trace_calls_unresolved / self.trace_calls_total, 4)

    # -- HSL023: traced-effect purity ------------------------------------------

    def purity_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program

        for traced in sorted(self.entry_bodies):
            node, host_fn = self.entry_bodies[traced]
            mod = prog.modules[host_fn.module]
            out += self._purity_walk(traced, node, mod, (traced,))

        for q in sorted(self.trace_fns):
            fn = prog.functions[q]
            mod = prog.modules[fn.module]
            # A function lexically decorated with a transform is already
            # inside HSL002's sight: its host-sync materializations are
            # per-file findings, and re-reporting them here would double
            # every `.item()`-in-jit. The closure-only effects (counters,
            # locks, clock, conf, IO) still report — HSL002 never checks
            # those.
            lexical = any(
                _transform_kind(d) is not None for d in fn.node.decorator_list
            )
            out += self._purity_walk(
                q, fn.node, mod, self.trace_fns[q], skip_host_sync=lexical
            )
        return out

    def _purity_walk(
        self, owner: str, node: ast.AST, mod: ModuleInfo, chain: tuple[str, ...],
        skip_host_sync: bool = False,
    ) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program
        witness = tuple(dict.fromkeys(
            prog.modules[prog.functions[c].module].path
            for c in chain if c in prog.functions
        )) or (mod.path,)
        via = " -> ".join(chain)

        def report(sub: ast.AST, what: str) -> None:
            if _suppressed(mod, sub.lineno, TRACED_EFFECT):
                return
            out.append(Finding(
                path=mod.path, line=sub.lineno, col=sub.col_offset,
                rule=TRACED_EFFECT,
                message=(
                    f"{what} inside the trace domain (traced via {via}) — "
                    f"jitted bodies must be host-effect-free: hoist the "
                    f"effect to the engagement site outside the traced "
                    f"function"
                ),
                witness_paths=witness,
            ))

        # Walk statement bodies only: decorator expressions (e.g. the
        # mesh argument of `@functools.partial(shard_map, mesh=...)`)
        # evaluate at definition time on the host, not inside the trace.
        for stmt in getattr(node, "body", []):
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        if isinstance(item.context_expr, (ast.Name, ast.Attribute)):
                            report(sub, "lock acquire")
                    continue
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                parts = dotted.split(".") if dotted else []
                tail = parts[-1] if parts else ""
                if isinstance(sub.func, ast.Attribute) and sub.func.attr in _HOST_SYNC_ATTRS:
                    if not skip_host_sync:
                        report(sub, f".{sub.func.attr}() host materialization")
                elif (
                    isinstance(sub.func, ast.Name)
                    and sub.func.id in _HOST_SYNC_CASTS
                    and sub.args
                    and not all(isinstance(a, ast.Constant) for a in sub.args)
                ):
                    if not skip_host_sync:
                        report(sub, f"{sub.func.id}() host cast of a traced value")
                elif tail in _NP_SYNC_FNS and parts[0] in ("np", "numpy"):
                    if not skip_host_sync:
                        report(sub, f"{dotted}() host materialization")
                elif dotted in ("jax.device_get", "device_get"):
                    if not skip_host_sync:
                        report(sub, "jax.device_get host transfer")
                elif tail == "increment":
                    report(sub, "stats counter increment")
                elif tail == "emit":
                    report(sub, "event emit")
                elif tail in ("fault_point", "inject"):
                    report(sub, "fault-point evaluation")
                elif tail == "open" or tail in _FILE_IO_TAILS:
                    report(sub, "file IO")
                elif dotted in _WALLCLOCK:
                    report(sub, f"wall-clock read {dotted}()")
                elif len(parts) >= 2 and parts[-2] == "conf" and tail in ("get", "set"):
                    report(sub, f"configuration {tail} via conf")
                elif tail == "acquire":
                    report(sub, "explicit lock acquire")
        return out

    # -- HSL024: signature-space boundedness -----------------------------------

    def signature_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program
        declared = self.static_domains
        used_static: set[str] = set()

        for e in self.entries:
            if e.kind == "pallas_kernel":
                continue
            host = prog.functions[e.host]
            mod = prog.modules[host.module]
            used_static.update(e.static_names)

            if not e.key_literal and not _suppressed(mod, e.line, SIGNATURE_SPACE):
                out.append(Finding(
                    path=mod.path, line=e.line, col=0, rule=SIGNATURE_SPACE,
                    message=(
                        f"jit key= at {e.host} is not a string literal — "
                        f"per-call keys defeat recompile-storm grouping; use "
                        f"one constant key per jit site"
                    ),
                    witness_paths=(mod.path,),
                ))

            bound = _lru_bound(host.node)
            if e.kind == "jit" and e.form == "call":
                # The bound-None nested-def case (a plain function
                # manufacturing jit(local_closure) per call) is HSL015's
                # finding — only the cases HSL015 cannot see report here:
                # an explicitly unbounded factory, or jit of a program
                # function outside any cache.
                unmemoized_program_fn = (
                    bound is None
                    and e.node is None
                    and not self._memo_stored(host, e)
                )
                if bound == "unbounded" or unmemoized_program_fn:
                    if not _suppressed(mod, e.line, SIGNATURE_SPACE):
                        out.append(Finding(
                            path=mod.path, line=e.line, col=0, rule=SIGNATURE_SPACE,
                            message=(
                                f"jit callable manufactured in {e.host} outside "
                                f"a bounded cache — wrap the factory in "
                                f"functools.lru_cache with a real maxsize (or "
                                f"store the callable in a locked memo "
                                f"container) so the set of live jit callables "
                                f"is finite"
                            ),
                            witness_paths=(mod.path,),
                        ))

            if declared is not None and bound != "bounded":
                for name in e.static_names:
                    if name in declared or _suppressed(mod, e.line, SIGNATURE_SPACE):
                        continue
                    out.append(Finding(
                        path=mod.path, line=e.line, col=0, rule=SIGNATURE_SPACE,
                        message=(
                            f"static argument {name!r} of {e.traced} is not "
                            f"declared in compat.KNOWN_STATIC_DOMAINS — every "
                            f"static value must come from a declared bounded "
                            f"domain, or each new value recompiles"
                        ),
                        witness_paths=(mod.path,),
                    ))

        out += self._stale_domain_findings(used_static)
        out += self._pad_findings()
        return out

    def _memo_stored(self, host: FunctionInfo, e: TraceEntry) -> bool:
        """True when the jit result is stored into a subscripted memo
        container inside the host (the HSL015-sanctioned idiom:
        ``fn = jit(raw, key=...); _CACHE[key] = fn``)."""
        jit_names: set[str] = set()
        for sub in ast.walk(host.node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            tgt, val = sub.targets[0], sub.value
            if (
                isinstance(val, ast.Call)
                and _dotted(val.func).rsplit(".", 1)[-1] in _TRANSFORMS
            ):
                if isinstance(tgt, ast.Name):
                    jit_names.add(tgt.id)
                elif isinstance(tgt, ast.Subscript):
                    return True
            elif (
                isinstance(tgt, ast.Subscript)
                and isinstance(val, ast.Name)
                and val.id in jit_names
            ):
                return True
        return False

    def _stale_domain_findings(self, used_static: set[str]) -> list[Finding]:
        """A KNOWN_STATIC_DOMAINS entry that no jit site uses as a
        static argument and no trace-hosting module uses as a parameter
        name is stale — the registry must stay honest both ways, like
        faults.KNOWN_POINTS."""
        declared = self.static_domains
        if not declared:
            return []
        prog = self.program
        host_modules = {prog.functions[e.host].module for e in self.entries}
        param_names: set[str] = set()
        for q, fn in prog.functions.items():
            if fn.module not in host_modules:
                continue
            args = fn.node.args
            for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
            ):
                param_names.add(a.arg)
        site = _registry_site(prog, "KNOWN_STATIC_DOMAINS")
        if site is None:
            return []
        mod, line = site
        out = []
        for name in sorted(declared - used_static - param_names):
            if _suppressed(mod, line, SIGNATURE_SPACE):
                continue
            out.append(Finding(
                path=mod.path, line=line, col=0, rule=SIGNATURE_SPACE,
                message=(
                    f"KNOWN_STATIC_DOMAINS entry {name!r} matches no static "
                    f"argument and no parameter of any trace-hosting module — "
                    f"remove the stale entry (the declared-registry contract)"
                ),
                witness_paths=(mod.path,),
            ))
        return out

    def _is_rounder(self, qname: str | None) -> bool:
        """A tile-rounding helper: a program function any of whose
        return expressions uses ``//``/``<<``/``%`` arithmetic (the
        ``_next_mult`` / ``next_pow2`` shape) — its results range over a
        bounded lattice of shapes, so pads derived from it cannot storm
        the compile cache."""
        fn = self.program.functions.get(qname or "")
        if fn is None:
            return False
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                for b in ast.walk(sub.value):
                    if isinstance(b, ast.BinOp) and isinstance(
                        b.op, (ast.FloorDiv, ast.LShift, ast.Mod)
                    ):
                        return True
            if isinstance(sub, ast.AugAssign) and isinstance(sub.op, ast.LShift):
                return True  # the `v <<= 1` loop body of next_pow2
        return False

    def _pad_findings(self) -> list[Finding]:
        """Shape-determining pad widths in trace-hosting modules must
        derive from a rounding helper: a width element that references a
        raw shape-derived local (``n = x.shape[0]`` / ``len(x)``) with
        no tile-rounded atom next to it recompiles once per distinct
        input length."""
        out: list[Finding] = []
        prog, cg = self.program, self.callgraph
        host_modules = {prog.functions[e.host].module for e in self.entries}

        for q in sorted(prog.functions):
            fn = prog.functions[q]
            if fn.module not in host_modules:
                continue
            mod = prog.modules[fn.module]
            shapeish: set[str] = set()
            rounded: set[str] = set()
            for sub in ast.walk(fn.node):
                if not isinstance(sub, ast.Assign):
                    continue
                names: list[str] = []
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        names.append(tgt.id)
                    elif isinstance(tgt, ast.Tuple):
                        names += [e.id for e in tgt.elts if isinstance(e, ast.Name)]
                if not names:
                    continue
                val = sub.value
                is_shape = any(
                    (isinstance(b, ast.Attribute) and b.attr in ("shape", "size"))
                    or (isinstance(b, ast.Call) and _dotted(b.func) == "len")
                    for b in ast.walk(val)
                )
                is_rounded = any(
                    isinstance(b, ast.Call)
                    and self._is_rounder(cg.resolve_call(fn, _dotted(b.func)))
                    for b in ast.walk(val)
                )
                if is_rounded:
                    rounded.update(names)
                elif is_shape:
                    shapeish.update(names)

            for sub in ast.walk(fn.node):
                if not isinstance(sub, ast.Call) or len(sub.args) < 2:
                    continue
                parts = _dotted(sub.func).split(".")
                if parts[-1] != "pad" or parts[0] not in ("jnp", "np", "numpy", "jax"):
                    continue
                widths = sub.args[1]
                elements = (
                    [e for t in widths.elts for e in (t.elts if isinstance(t, ast.Tuple) else [t])]
                    if isinstance(widths, (ast.Tuple, ast.List))
                    else [widths]
                )
                for el in elements:
                    names = {b.id for b in ast.walk(el) if isinstance(b, ast.Name)}
                    if names & shapeish and not names & rounded:
                        if _suppressed(mod, sub.lineno, SIGNATURE_SPACE):
                            continue
                        out.append(Finding(
                            path=mod.path, line=sub.lineno, col=sub.col_offset,
                            rule=SIGNATURE_SPACE,
                            message=(
                                f"pad width in {q} derives from a raw "
                                f"data-dependent shape ({', '.join(sorted(names & shapeish))}) "
                                f"with no tile-rounding — every distinct input "
                                f"length mints a new compile signature; round "
                                f"the target size first (_next_mult idiom)"
                            ),
                            witness_paths=(mod.path,),
                        ))
        return out

    # -- HSL025: donation/aliasing safety --------------------------------------

    def donation_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog, cg = self.program, self.callgraph
        producers: list[dict] = []
        gateways: list[dict] = []
        gateway_fns: set[str] = set()
        staged_by_fn: dict[str, dict[str, set[str]]] = {}

        for q in sorted(prog.functions):
            fn = prog.functions[q]
            mod = prog.modules[fn.module]
            staged: set[str] = set()
            owned: set[str] = set()
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt, val = sub.targets[0], sub.value
                    if not (isinstance(tgt, ast.Name) and isinstance(val, ast.Call)):
                        continue
                    tail = _dotted(val.func).rsplit(".", 1)[-1]
                    if tail == "stage_column":
                        staged.add(tgt.id)
                        producers.append({"fn": q, "line": sub.lineno, "kind": "stage_column"})
                    elif tail == "from_arrow" and any(
                        kw.arg == "zero_copy_ok"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in val.keywords
                    ):
                        staged.add(tgt.id)
                        producers.append(
                            {"fn": q, "line": sub.lineno, "kind": "zero_copy_from_arrow"}
                        )
                elif isinstance(sub, ast.Call):
                    d = _dotted(sub.func)
                    if d.rsplit(".", 1)[-1] == "own_arrays":
                        root = d.split(".")[0]
                        owned.add(root)
                        gateways.append({"fn": q, "line": sub.lineno})
                        gateway_fns.add(q)
            staged_by_fn[q] = {"staged": staged, "owned": owned}

            # In-place mutation of a staged view.
            for sub in ast.walk(fn.node):
                tgts: list[ast.AST] = []
                if isinstance(sub, ast.Assign):
                    tgts = [t for t in sub.targets if isinstance(t, ast.Subscript)]
                elif isinstance(sub, ast.AugAssign) and isinstance(sub.target, ast.Subscript):
                    tgts = [sub.target]
                for t in tgts:
                    root = _sub_root(t)
                    if root in staged and root not in owned:
                        if _suppressed(mod, sub.lineno, DONATION_SAFETY):
                            continue
                        out.append(Finding(
                            path=mod.path, line=sub.lineno, col=sub.col_offset,
                            rule=DONATION_SAFETY,
                            message=(
                                f"in-place mutation of zero-copy staged view "
                                f"{root!r} in {q} — writeable=False staged "
                                f"arrays are identity-stable by contract; call "
                                f"ColumnTable.own_arrays() (copying ownership "
                                f"gateway) before mutating"
                            ),
                            witness_paths=(mod.path,),
                        ))

        donation_sites = [e for e in self.entries if e.donates]
        for e in donation_sites:
            traced_fn = prog.functions.get(e.traced)
            if traced_fn is None:
                continue
            params = [a.arg for a in traced_fn.node.args.args]
            idxs = set(e.donate_nums)
            idxs.update(params.index(n) for n in e.donate_names if n in params)
            for q in sorted(prog.functions):
                fn = prog.functions[q]
                mod = prog.modules[fn.module]
                info = staged_by_fn.get(q, {"staged": set(), "owned": set()})
                for sub in ast.walk(fn.node):
                    if not isinstance(sub, ast.Call):
                        continue
                    if cg.resolve_call(fn, _dotted(sub.func)) != e.traced:
                        continue
                    for i in sorted(idxs):
                        if i >= len(sub.args):
                            continue
                        root = _sub_root(sub.args[i]) if not isinstance(
                            sub.args[i], ast.Name
                        ) else sub.args[i].id
                        if root is None:
                            continue
                        if root in info["staged"] and root not in info["owned"]:
                            if not _suppressed(mod, sub.lineno, DONATION_SAFETY):
                                out.append(Finding(
                                    path=mod.path, line=sub.lineno, col=sub.col_offset,
                                    rule=DONATION_SAFETY,
                                    message=(
                                        f"zero-copy staged view {root!r} donated "
                                        f"to {e.traced} in {q} — donation frees "
                                        f"the buffer the Arrow table still "
                                        f"aliases; own_arrays() first"
                                    ),
                                    witness_paths=(mod.path,),
                                ))
                        used_after = any(
                            isinstance(b, ast.Name)
                            and b.id == root
                            and isinstance(b.ctx, ast.Load)
                            and b.lineno > (sub.end_lineno or sub.lineno)
                            for b in ast.walk(fn.node)
                        )
                        if used_after and not _suppressed(mod, sub.lineno, DONATION_SAFETY):
                            out.append(Finding(
                                path=mod.path, line=sub.lineno, col=sub.col_offset,
                                rule=DONATION_SAFETY,
                                message=(
                                    f"buffer {root!r} is referenced after being "
                                    f"donated to {e.traced} in {q} — a donated "
                                    f"buffer is dead after the call on every "
                                    f"path; copy first or drop the reference"
                                ),
                                witness_paths=(mod.path,),
                            ))

        self._donation = {
            "staged_view_producers": [
                {
                    **p,
                    "ownership_witness": cg.find_path(p["fn"], gateway_fns)
                    if gateway_fns else None,
                }
                for p in producers
            ],
            "donation_sites": [
                {"fn": e.host, "line": e.line, "traced": e.traced}
                for e in donation_sites
            ],
            "own_arrays_gateways": gateways,
            "proven": True,  # flipped below if findings exist
        }
        if out:
            self._donation["proven"] = False
        return out

    # -- HSL026: kernel fallback-ladder completeness ---------------------------

    def _build_ladders(self) -> list[dict]:
        prog, cg = self.program, self.callgraph
        ladders: list[dict] = []
        pallas_hosts: dict[str, int] = {}
        for e in self.entries:
            if e.kind == "pallas_kernel" and e.host not in pallas_hosts:
                pallas_hosts[e.host] = e.line

        for host in sorted(pallas_hosts):
            key = next(
                (e.key for e in self.entries
                 if e.host == host and e.kind == "jit" and e.key),
                None,
            )
            factory = prog.functions[host]
            engagement: dict[str, list[str]] = {host: [host]}
            for q in sorted(prog.functions):
                if q == host or prog.functions[q].module != factory.module:
                    continue
                path = cg.find_path(q, {host})
                if path is not None:
                    engagement[q] = path

            gate = bad_set = None
            bad_add = False
            counters: dict[str, tuple[str, int]] = {}
            for q in sorted(engagement):
                fn = prog.functions[q]
                for sub in ast.walk(fn.node):
                    # Gate: any comparison against an uppercase bound
                    # constant — whether in an `if` test or assigned to
                    # an eligibility flag (topk's `use_pallas = ...`).
                    if isinstance(sub, ast.Compare) and gate is None:
                        for b in ast.walk(sub):
                            if isinstance(b, ast.Name) and _uppercase_const(b.id):
                                gate = {"fn": q, "line": sub.lineno}
                                break
                    if isinstance(sub, ast.Compare) and bad_set is None:
                        if any(isinstance(op, (ast.In, ast.NotIn)) for op in sub.ops):
                            for b in ast.walk(sub):
                                if (
                                    isinstance(b, (ast.Name, ast.Attribute))
                                    and "bad" in (_dotted(b) or "").lower()
                                ):
                                    bad_set = {"fn": q, "line": sub.lineno}
                                    break
                    if isinstance(sub, ast.Call):
                        d = _dotted(sub.func)
                        if (
                            d.rsplit(".", 1)[-1] == "add"
                            and "bad" in d.lower()
                        ):
                            bad_add = True
                        if (
                            d.rsplit(".", 1)[-1] == "increment"
                            and sub.args
                            and isinstance(sub.args[0], ast.Constant)
                            and isinstance(sub.args[0].value, str)
                            and sub.args[0].value.startswith("device.kernel.")
                        ):
                            counters.setdefault(sub.args[0].value, (q, sub.lineno))

            witness = max(engagement.values(), key=len)
            ladders.append({
                "kernel": key or host,
                "factory": host,
                "line": pallas_hosts[host],
                "engagement": sorted(engagement),
                "gate": gate,
                "bad_set": bad_set if (bad_set and bad_add) else None,
                "counters": {
                    name: {"fn": counters[name][0], "line": counters[name][1]}
                    for name in sorted(counters)
                },
                "witness": witness,
                "proven": bool(
                    gate and bad_set and bad_add
                    and any("fallback" in c for c in counters)
                    and any("fallback" not in c for c in counters)
                ),
            })
        return ladders

    def kernel_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program
        declared = self.known_kernels
        found_names = {lad["kernel"] for lad in self._kernel_ladders}

        for lad in self._kernel_ladders:
            host = lad["factory"]
            mod = prog.modules[prog.functions[host].module]
            witness = tuple(dict.fromkeys(
                prog.modules[prog.functions[q].module].path
                for q in lad["engagement"] if q in prog.functions
            ))
            if declared is not None and lad["kernel"] not in declared:
                if not _suppressed(mod, lad["line"], KERNEL_LADDER):
                    out.append(Finding(
                        path=mod.path, line=lad["line"], col=0, rule=KERNEL_LADDER,
                        message=(
                            f"Pallas engagement {lad['kernel']!r} (factory "
                            f"{host}) is not declared in ops.KNOWN_KERNELS — "
                            f"declare it so the fallback ladder is tracked "
                            f"(the declared-registry contract)"
                        ),
                        witness_paths=witness,
                    ))
            missing = []
            if lad["gate"] is None:
                missing.append("exactness/eligibility gate (compare against an "
                               "uppercase bound constant)")
            if lad["bad_set"] is None:
                missing.append("permanent per-shape fallback (a *bad* set "
                               "consulted with `in` and grown with .add)")
            if not any("fallback" not in c for c in lad["counters"]):
                missing.append("success counter (device.kernel.* increment on "
                               "the engaged path)")
            if not any("fallback" in c for c in lad["counters"]):
                missing.append("fallback counter (device.kernel.* increment on "
                               "the fallback path)")
            if missing and not _suppressed(mod, lad["line"], KERNEL_LADDER):
                chain = " -> ".join(lad["witness"])
                out.append(Finding(
                    path=mod.path, line=lad["line"], col=0, rule=KERNEL_LADDER,
                    message=(
                        f"Pallas kernel {lad['kernel']!r} has an incomplete "
                        f"fallback ladder (engagement chain {chain}): missing "
                        + "; ".join(missing)
                    ),
                    witness_paths=witness,
                ))
            if self.known_counters is not None:
                for cname in sorted(lad["counters"]):
                    if cname in self.known_counters:
                        continue
                    site = lad["counters"][cname]
                    if not _suppressed(mod, site["line"], KERNEL_LADDER):
                        out.append(Finding(
                            path=mod.path, line=site["line"], col=0,
                            rule=KERNEL_LADDER,
                            message=(
                                f"kernel counter {cname!r} is not declared in "
                                f"stats.KNOWN_COUNTERS — undeclared names "
                                f"raise at runtime"
                            ),
                            witness_paths=witness,
                        ))

        if declared is not None:
            site = _registry_site(prog, "KNOWN_KERNELS")
            if site is not None:
                mod, line = site
                for name in sorted(declared - found_names):
                    if _suppressed(mod, line, KERNEL_LADDER):
                        continue
                    out.append(Finding(
                        path=mod.path, line=line, col=0, rule=KERNEL_LADDER,
                        message=(
                            f"KNOWN_KERNELS entry {name!r} matches no Pallas "
                            f"engagement in the scanned program — remove the "
                            f"stale entry (the declared-registry contract)"
                        ),
                        witness_paths=(mod.path,),
                    ))
        return out

    # -- driver ----------------------------------------------------------------

    def findings(self) -> list[Finding]:
        if self._findings is None:
            out: list[Finding] = []
            out += self.purity_findings()
            out += self.signature_findings()
            out += self.donation_findings()
            out += self.kernel_findings()
            self._findings = out
        return self._findings

    def to_json(self) -> dict:
        self.findings()  # materialize the donation proof
        entries: dict[str, dict] = {}
        for e in sorted(self.entries, key=lambda e: (e.traced, e.line, e.kind)):
            cur = entries.setdefault(e.traced, {
                "kinds": [], "site": e.host, "line": e.line,
                "key": None, "static": [], "donates": False,
            })
            if e.kind not in cur["kinds"]:
                cur["kinds"].append(e.kind)
                cur["kinds"].sort()
            if e.key and cur["key"] is None:
                cur["key"] = e.key
            cur["static"] = sorted(set(cur["static"]) | set(e.static_names))
            cur["donates"] = cur["donates"] or e.donates
        return {
            "entries": entries,
            "trace_functions": {
                q: list(chain) for q, chain in sorted(self.trace_fns.items())
            },
            "unresolved": {
                "total": self.trace_calls_total,
                "unresolved": self.trace_calls_unresolved,
                "ratio": self.unresolved_ratio(),
            },
            "donation_proof": self._donation,
            "kernel_ladders": self._kernel_ladders,
        }
