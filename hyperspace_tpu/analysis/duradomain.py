"""Durability-domain analysis: crash-consistency as theorems (HSL027-030).

The whole thesis of this repo is index metadata as a log-backed catalog
with crash-safe two-phase commits, and the ingest/fleet/observability
layers multiplied the durable surfaces that thesis rests on — ingest
cursors and control files, CDC delta batches, heal markers, journal
segments, incident bundles, shared-cache entries, the advisor ledger.
Each carries a hand-maintained atomic-publish / write-ordering /
replay-idempotence protocol that, until this layer, only the dynamic
crash sweeps exercised. This module is the durability dual of
:mod:`procdomain`/:mod:`tracedomain`: instead of inferring which code
runs in which *process* or *trace*, it infers which code **writes which
durable root**, then turns each protocol into a checked rule.

- **The durability-domain inference.** :data:`DURABLE_ROOTS` declares
  every durable file plane by path marker (AST-extracted from any
  scanned module, exactly like ``SPAWN_ENTRY_POINTS`` — fixture
  packages declare their own). A *durable write site* is any raw write
  (``open(.., "w")``/``write_text``/``write_bytes``/``os.open`` with
  ``O_WRONLY``), atomic publish (``os.replace``/``os.rename``/
  ``os.link``), or delegation to a program function that transitively
  writes, whose call text — widened through local path bindings and
  ``self.<attr>`` accessor bodies, the HSL021 mechanics — names a
  declared root. The *durability domain* is every function whose
  call-graph closure contains such a site (the reverse closure of the
  writing functions, dispatch-augmented, with witness chains).

- **HSL027 atomic-publish completeness.** Every durable write must
  reach the sanctioned idiom: an ``os.replace``/``os.rename``/
  ``os.link`` publish with an ``fsync`` strictly BEFORE it in the same
  function (``file_utils._overwrite_json`` is the exemplar), or a
  delegation chain to a function that proves it. A publish with no
  fsync-before-replace can surface a zero-length file after a crash —
  the rename is durable before the data is. This generalizes HSL021
  from lease/fleet paths to every declared durable root; lease/fleet
  write sites this rule claims are deduplicated out of HSL021 so
  ``--changed`` runs report each site exactly once, under the newer
  rule. ``O_EXCL`` claims stay HSL021's (the TTL-reap proof lives
  there); ``os.rename`` inside a TTL-reaper is a lease clear, not a
  durable publish, and is exempt.

- **HSL028 torn-window ordering.** :data:`TORN_WINDOWS` declares every
  exactly-once protocol as (function, first-write pattern, second-write
  pattern, in-window fault point): batch-published-before-cursor-saved,
  commit-before-lag-stamp, segment-sealed-before-eviction-index,
  marker-after-heal. The rule proves, statically, that the two writes
  are ordered on every path (every textual occurrence of the first
  precedes every occurrence of the second) AND that a declared
  ``faults.KNOWN_POINTS`` entry is armed strictly inside the window —
  so the dynamic crash sweeps (tests/test_ingest.py, test_journal.py,
  test_controller.py parametrize over this registry by name) provably
  exercise each torn state and can never drift from the static list.

- **HSL029 replay-idempotence.** :data:`REPLAY_ROOTS` declares the
  recovery/re-poll/takeover entry points. Any durable write site in
  their call-graph closure must derive its file name from cursor /
  log-id / generation values — never wall clock, pid, or RNG — making
  the "a retry rewrites the SAME file at the SAME path" contract a
  theorem instead of a comment.

- **HSL030 snapshot-stamp discipline.** Code in a pinned-snapshot
  context — any function carrying a ``snapshot``/``snap`` parameter,
  plus the unguarded closure it calls into — must key caches on the
  snapshot's ``stamp`` and never read the live version vector
  (``get_latest_id``/``collection_log_versions``/``latest_log_id``).
  A conditional whose test names the snapshot parameter marks BOTH
  branches as the sanctioned pinned-vs-live dispatch
  (``plan_cache.versioned_plan_key`` is the exemplar).

Everything here is stdlib-``ast`` only and never imports analyzed code,
same as the rest of the engine (docs/static_analysis.md).
"""

from __future__ import annotations

import ast
import dataclasses
import re

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.lint import Finding, _dotted
from hyperspace_tpu.analysis.procdomain import ProcessDomains, _suppressed
from hyperspace_tpu.analysis.program import FunctionInfo, ModuleInfo, Program
from hyperspace_tpu.analysis.raises import known_fault_points

ATOMIC_PUBLISH = "HSL027"
TORN_WINDOW = "HSL028"
REPLAY_IDEMPOTENCE = "HSL029"
SNAPSHOT_STAMP = "HSL030"

#: The real registry: every durable file plane of this package, by the
#: path-marker text that names it in write-call expressions (lowered
#: substring match over the call segment widened with local bindings
#: and ``self.<attr>`` accessor bodies). AST-extracted from this module
#: when the package is scanned — fixture packages and corpus files
#: declare their own ``DURABLE_ROOTS`` literal the same way. Keep it a
#: plain dict literal of string constants.
DURABLE_ROOTS = {
    "hyperspace_log": "the op log: version entries + transient markers",
    "latest_stable": "the latestStable pointer (2-phase commit anchor)",
    "_ingest": "ingest state dir: cursors + pause/resume control",
    "control_file": "ingest control file (pause/resume, atomic JSON)",
    "cursor": "per-index ingest cursors (offset/seq/seen-set)",
    "cdc-": "streaming CDC delta batches (seq-named parquet)",
    "advisor_dir": "the _advisor routing ledger",
    "lease": "fleet cross-process lease files",
    "heal": "fleet heal markers (generation-stamped)",
    "entry_path": "fleet shared plan-cache entries",
    "segment_prefix": "telemetry journal segments (sealed jsonl)",
    "bundle": "controller incident bundles",
    "incident": "controller incident state",
}

#: Declared exactly-once protocols: window name -> (function qname,
#: first-write pattern, second-write pattern, in-window fault point,
#: why). The dynamic crash sweeps parametrize over this registry BY
#: NAME (tests/test_ingest.py, test_journal.py, test_controller.py), so
#: the static window list and the sweep can never drift apart.
TORN_WINDOWS = {
    "ingest.cdc.batch_before_cursor": (
        "hyperspace_tpu.ingest.tailer.CdcTailer.poll",
        "_write_batch", "cursor.save", "ingest.tail",
        "a CDC batch file lands before the cursor advances; the re-poll "
        "rewrites the same seq-named file"),
    "ingest.commit_before_lag_stamp": (
        "hyperspace_tpu.ingest.daemon.IngestDaemon._tick_index",
        "commit_micro_batch", "_last_commit_id", "ingest.stamp",
        "a micro-batch commits before the daemon stamps lag/commit "
        "bookkeeping; recover() converges the log, the next tick restamps"),
    "journal.seal_before_index": (
        "hyperspace_tpu.obs.journal._seal_locked",
        "os.replace", "_evict_locked", "journal.seal",
        "a sealed segment is published before the eviction index runs; "
        "sweep() re-lists and merges the orphan segment"),
    "controller.marker_after_heal": (
        "hyperspace_tpu.serve.controller.OpsController._heal",
        "_heal_local", "_write_marker", "controller.heal.marker",
        "the shared bytes heal before the generation marker publishes; "
        "followers re-heal idempotently on the next tick"),
}

#: Recovery / re-poll / takeover entry points: every durable file name
#: reachable from these must derive from cursor/log-id/generation
#: values (HSL029) so a replay rewrites the same paths.
REPLAY_ROOTS = {
    "hyperspace_tpu.ingest.tailer.CdcTailer.poll":
        "CDC re-poll after a crash rewrites the same seq-named batch",
    "hyperspace_tpu.hyperspace.Hyperspace.recover":
        "log recovery: quarantine/roll-forward rewrites version-named state",
    "hyperspace_tpu.serve.fleet.singleflight.SingleFlight.run":
        "single-flight takeover re-runs the build under the same key",
}

#: Publish tails: the call that makes a durable name visible.
_PUBLISH_TAILS = ("replace", "rename", "link")
#: A rename whose destination carries one of these is a quarantine /
#: tombstone move — it takes a file OUT of the durable namespace
#: (recover()'s `.corrupt` aside, a reaper's `.reap-` steal), so there
#: is no payload whose durability must precede the name.
_TOMBSTONE_MARKERS = ("corrupt", "quarantine", "tombstone", ".reap")
#: Durability barrier tails: must precede the publish in the same fn.
_FSYNC_TAILS = ("fsync", "_fsync_dir", "fsync_dir")
#: Snapshot-context parameter names (HSL030 carriers).
_SNAPSHOT_PARAMS = ("snapshot", "snap")
#: Live version-vector reads banned inside a pinned-snapshot context.
_LIVE_READ_TAILS = ("get_latest_id", "collection_log_versions")
_LIVE_READ_ATTR = "latest_log_id"
#: Nondeterministic name atoms (HSL029): a durable file name derived
#: from any of these cannot be rewritten identically on replay.
_NONDETERMINISTIC = (
    "time.time", "time_ns", "monotonic", "perf_counter", "datetime.now",
    "utcnow", "getpid", "uuid4", "uuid1", "token_hex", "urandom",
    "randint", "randrange", "random.random",
)

_SELF_REF_RE = re.compile(r"self\.([a-z_][a-z0-9_]*)")


def _seg(mod: ModuleInfo, node: ast.AST) -> str:
    """Source text of ``node`` against the module's precomputed line
    table — ``ast.get_source_segment`` re-splits the whole module source
    on every call, which made the site sweep quadratic in practice."""
    l0 = getattr(node, "lineno", None)
    l1 = getattr(node, "end_lineno", None)
    if l0 is None or l1 is None:
        return ""
    c0, c1 = node.col_offset, node.end_col_offset
    lines = mod.lines
    if l0 < 1 or l1 > len(lines):
        return ""
    if l0 == l1:
        return lines[l0 - 1][c0:c1]
    return "\n".join([lines[l0 - 1][c0:], *lines[l0:l1 - 1], lines[l1 - 1][:c1]])


def _dict_registry(program: Program, name: str) -> dict[str, tuple[str, ...]] | None:
    """The union of every scanned module's top-level ``<name>`` dict
    literal, values normalized to string tuples; None when no module
    declares one — the rules that read it disarm, so a corpus file
    scanned alone reports nothing it didn't declare."""
    out: dict[str, tuple[str, ...]] | None = None
    for mod in program.modules.values():
        for node in mod.tree.body:
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not (isinstance(target, ast.Name) and target.id == name):
                continue
            if not isinstance(value, ast.Dict):
                continue
            out = out or {}
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out[k.value] = (v.value,)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    out[k.value] = tuple(
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    )
    return out


@dataclasses.dataclass
class WriteSite:
    """One durable write: a raw write, an atomic publish, or a
    delegated call into a function that transitively writes."""

    fn: str                      # containing function qname
    line: int
    kind: str                    # "raw" | "publish" | "delegated"
    root: str                    # the DURABLE_ROOTS marker matched
    seg: str                     # widened, lowered call text (HSL029 input)
    ok: bool = True              # proves (or delegates to) the idiom
    target: str | None = None    # delegation target, when kind=="delegated"
    chain: tuple[str, ...] = ()  # delegation witness chain


@dataclasses.dataclass
class _FnWrites:
    """Per-function write profile (the HSL027 proof obligations)."""

    raw_lines: list[int] = dataclasses.field(default_factory=list)
    publish: list[tuple[str, int]] = dataclasses.field(default_factory=list)
    fsync_lines: list[int] = dataclasses.field(default_factory=list)

    @property
    def writes(self) -> bool:
        return bool(self.raw_lines or self.publish)

    @property
    def proven(self) -> bool:
        """fsync-before-publish in the same function body."""
        return any(
            any(f < line for f in self.fsync_lines)
            for _, line in self.publish
        )


class DurabilityDomains:
    """Infer the durability domain and check HSL027-030 over it.

    Same engine contract as :class:`procdomain.ProcessDomains`: built
    from the program summaries and call graph, never importing analyzed
    code; ``findings()`` returns the rule violations and ``to_json()``
    the inferred graph (golden-tested for the durademo fixture and
    shipped in the check report's ``durable_domains`` section).
    ``claimed_sites`` is the HSL021-dedupe surface check.py consumes.
    """

    def __init__(self, program: Program, callgraph: CallGraph, raises=None):
        self.program = program
        self.callgraph = callgraph
        self.raises = raises

        roots = _dict_registry(program, "DURABLE_ROOTS")
        self.roots: dict[str, str] | None = (
            {k: v[0] if v else "" for k, v in roots.items()}
            if roots is not None else None
        )
        self.windows = _dict_registry(program, "TORN_WINDOWS")
        replay = _dict_registry(program, "REPLAY_ROOTS")
        self.replay_roots: dict[str, str] | None = (
            {k: v[0] if v else "" for k, v in replay.items()}
            if replay is not None else None
        )
        self.known_points, _ = known_fault_points(program)

        #: per-function write profiles (all functions, marker-blind)
        self._profiles: dict[str, _FnWrites] = {}
        #: durable write sites (direct + delegated), marker-matched
        self.sites: list[WriteSite] = []
        #: (path, line) of every HSL027-checked site — check.py drops
        #: HSL021 findings on these so each site reports once
        self.claimed_sites: set[tuple[str, int]] = set()
        #: durability domain: qname -> witness chain down to a writer
        self.domain_fns: dict[str, tuple[str, ...]] = {}
        #: replay closure: qname -> chain from its replay root
        self.replay_fns: dict[str, tuple[str, ...]] = {}
        self.dura_calls_total = 0
        self.dura_calls_unresolved = 0
        self._delegation_memo: dict[str, tuple[tuple[str, ...] | None,
                                               tuple[str, ...] | None]] = {}

        if self.roots is not None:
            self._build_profiles()
            self._find_sites()
            self._build_domain()
        self._window_proofs = self._build_window_proofs()
        if self.replay_roots is not None:
            self._build_replay_closure()
        self._findings: list[Finding] | None = None

    # -- write-site detection --------------------------------------------------

    def _build_profiles(self) -> None:
        for q, fn in self.program.functions.items():
            prof = _FnWrites()
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func)
                tail = dotted.split(".")[-1]
                if tail in _FSYNC_TAILS:
                    prof.fsync_lines.append(node.lineno)
                elif tail in _PUBLISH_TAILS:
                    prof.publish.append((tail, node.lineno))
                elif self._is_raw_write(node, dotted, tail):
                    prof.raw_lines.append(node.lineno)
            if prof.writes or prof.fsync_lines:
                self._profiles[q] = prof

    @staticmethod
    def _is_raw_write(node: ast.Call, dotted: str, tail: str) -> bool:
        if tail in ("write_text", "write_bytes"):
            return True
        if tail != "open":
            return False
        if dotted.startswith("os"):
            # os.open flags ride in the source text; O_EXCL claims are
            # HSL021's (lease protocol), not bare durable writes.
            return False
        mode = ProcessDomains._open_mode(node)
        return mode is not None and any(c in mode for c in "wax+")

    def _binds(self, mod: ModuleInfo, fn: FunctionInfo) -> dict[str, str]:
        """Local name -> lowered source text of its first binding
        (single-name and tuple-unpack assigns: ``fd, tmp = mkstemp(..)``
        binds BOTH names to the mkstemp call text)."""
        binds: dict[str, str] = {}
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            tgt = sub.targets[0]
            names: list[str] = []
            if isinstance(tgt, ast.Name):
                names = [tgt.id]
            elif isinstance(tgt, ast.Tuple):
                names = [e.id for e in tgt.elts if isinstance(e, ast.Name)]
            if not names:
                continue
            txt = _seg(mod, sub.value).lower()
            for n in names:
                binds.setdefault(n, txt)
        return binds

    def _self_attr_text(self, fn: FunctionInfo, attr: str, depth: int = 2) -> str:
        """Lowered source text of ``self.<attr>``: the return expression
        of an accessor method/property, or the ``__init__`` binding —
        how ``write_json(self.control_path, ...)`` learns it writes
        under ``_ingest`` (one level of further self.* references is
        chased so ``control_path -> _state_dir`` resolves too)."""
        if depth <= 0 or fn.cls is None:
            return ""
        cls = self.program.classes.get(f"{fn.module}.{fn.cls}")
        if cls is None:
            return ""
        mod = self.program.modules.get(fn.module)
        if mod is None:
            return ""
        out = ""
        m = cls.methods.get(attr)
        if m is not None:
            for sub in ast.walk(m.node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    out += " " + _seg(mod, sub.value).lower()
        init = cls.methods.get("__init__")
        if init is not None:
            for sub in ast.walk(init.node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and tgt.attr == attr
                    ):
                        out += " " + _seg(mod, sub.value).lower()
        for ref in set(_SELF_REF_RE.findall(out)):
            if ref != attr:
                out += self._self_attr_text(fn, ref, depth - 1)
        return out

    def _widen(self, mod: ModuleInfo, fn: FunctionInfo, node: ast.Call,
               binds: dict[str, str], args: int = 1) -> str:
        seg = _seg(mod, node).lower()
        candidates: list[ast.expr] = list(node.args[:args])
        if isinstance(node.func, ast.Attribute):
            candidates.append(node.func.value)
        for expr in candidates:
            for name in ast.walk(expr):
                if isinstance(name, ast.Name) and name.id in binds:
                    seg += " " + binds[name.id]
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                seg += self._self_attr_text(fn, expr.attr)
        # One more hop: a bind like `p = self.log_dir / str(id)` names
        # the root only through the attribute's __init__ binding
        # (`self.log_dir = self.index_path / HYPERSPACE_LOG_DIR`).
        for ref in set(_SELF_REF_RE.findall(seg)):
            seg += self._self_attr_text(fn, ref, depth=1)
        return seg

    def _marker(self, seg: str) -> str | None:
        for marker in self.roots or ():
            if marker.lower() in seg:
                return marker
        return None

    def _find_sites(self) -> None:
        prog, cg = self.program, self.callgraph
        for q in sorted(prog.functions):
            fn = prog.functions[q]
            mod = prog.modules[fn.module]
            if mod.path.endswith("faults.py"):
                continue  # the injection harness corrupts files BY DESIGN
            binds = self._binds(mod, fn)
            is_reaper = ProcessDomains._is_reaper(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func)
                tail = dotted.split(".")[-1]
                if tail in _FSYNC_TAILS:
                    continue
                if tail in _PUBLISH_TAILS:
                    if is_reaper and tail in ("rename", "unlink"):
                        continue  # lease clear, not a durable publish
                    seg = self._widen(mod, fn, node, binds, args=2)
                    if any(t in seg for t in _TOMBSTONE_MARKERS):
                        continue  # quarantine/tombstone move, not a publish
                    marker = self._marker(seg)
                    if marker is None:
                        continue
                    prof = self._profiles.get(q, _FnWrites())
                    ok = any(f < node.lineno for f in prof.fsync_lines)
                    self.sites.append(WriteSite(
                        fn=q, line=node.lineno, kind="publish", root=marker,
                        seg=seg, ok=ok,
                    ))
                elif self._is_raw_write(node, dotted, tail):
                    seg = self._widen(mod, fn, node, binds)
                    marker = self._marker(seg)
                    if marker is None:
                        continue
                    prof = self._profiles.get(q, _FnWrites())
                    self.sites.append(WriteSite(
                        fn=q, line=node.lineno, kind="raw", root=marker,
                        seg=seg, ok=prof.proven,
                    ))
                else:
                    target = cg.resolve_call(fn, dotted) if dotted else None
                    if target is None or target not in prog.functions:
                        continue
                    seg = self._widen(mod, fn, node, binds)
                    marker = self._marker(seg)
                    if marker is None:
                        continue
                    writers, proven = self._delegation(target)
                    if writers is None:
                        continue  # the callee closure never writes
                    self.sites.append(WriteSite(
                        fn=q, line=node.lineno, kind="delegated", root=marker,
                        seg=seg, ok=proven is not None, target=target,
                        chain=proven if proven is not None else writers,
                    ))
        for s in self.sites:
            mod = prog.modules[prog.functions[s.fn].module]
            self.claimed_sites.add((mod.path, s.line))

    def _exempt_writer(self, q: str) -> bool:
        """Writers whose writes are not durable publishes BY DESIGN:
        the fault-injection harness (``_mangle_file`` corrupts files on
        purpose — that IS the torn write being simulated) and TTL
        reapers (their rename/unlink is a lease CLEAR, proven by
        HSL021's reap check, not a data publish)."""
        fn = self.program.functions.get(q)
        if fn is None:
            return True
        mod = self.program.modules.get(fn.module)
        if mod is not None and mod.path.endswith("faults.py"):
            return True
        return ProcessDomains._is_reaper(fn)

    def _delegation(self, start: str) -> tuple[tuple[str, ...] | None,
                                               tuple[str, ...] | None]:
        """Chase a delegated write through resolved calls AND
        function-valued call arguments (``retry.retry_call(
        _overwrite_json, path, data)`` passes the writer as data).
        Returns (chain-to-some-writer | None, chain-to-proven-writer |
        None) — (None, None) means the closure never writes."""
        if start in self._delegation_memo:
            return self._delegation_memo[start]
        prog, cg = self.program, self.callgraph
        writer_chain: tuple[str, ...] | None = None
        proven_chain: tuple[str, ...] | None = None
        visited: set[str] = set()
        stack: list[tuple[str, tuple[str, ...]]] = [(start, (start,))]
        while stack:
            q, chain = stack.pop()
            if q in visited:
                continue
            visited.add(q)
            fn = prog.functions.get(q)
            if fn is None:
                continue
            prof = self._profiles.get(q)
            if prof is not None and prof.writes and not self._exempt_writer(q):
                if writer_chain is None or len(chain) < len(writer_chain):
                    writer_chain = chain
                if prof.proven and (
                    proven_chain is None or len(chain) < len(proven_chain)
                ):
                    proven_chain = chain
            nexts: set[str] = set()
            for call in fn.calls:
                got = cg.resolve_call(fn, call.raw)
                if got is not None:
                    nexts.update(self._dispatch(got))
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                for arg in node.args:
                    if not isinstance(arg, (ast.Name, ast.Attribute)):
                        continue
                    raw = _dotted(arg)
                    got = cg.resolve_call(fn, raw) if raw else None
                    if got is not None and got in prog.functions:
                        nexts.add(got)
            for t in sorted(nexts):
                if t not in visited:
                    stack.append((t, (*chain, t)))
        self._delegation_memo[start] = (writer_chain, proven_chain)
        return writer_chain, proven_chain

    # -- the durability domain (reverse closure of the writers) ----------------

    def _dispatch(self, callee: str) -> tuple[str, ...]:
        if self.raises is not None:
            return self.raises.dispatch_targets(callee)
        return (callee,)

    def _build_domain(self) -> None:
        prog, cg = self.program, self.callgraph
        radj: dict[str, set[str]] = {}
        for e in cg.edges:
            for t in self._dispatch(e.callee):
                radj.setdefault(t, set()).add(e.caller)
        stack: list[str] = []
        for s in self.sites:
            if s.fn not in self.domain_fns:
                self.domain_fns[s.fn] = (s.fn,)
                stack.append(s.fn)
        while stack:
            q = stack.pop()
            for caller in sorted(radj.get(q, ())):
                if caller not in self.domain_fns:
                    self.domain_fns[caller] = (caller, *self.domain_fns[q])
                    stack.append(caller)
        # Blind-spot accounting over the domain (the tracedomain ratio
        # contract): unresolved calls made BY domain functions weaken
        # both the delegation proofs and the replay closure.
        unresolved_by: dict[str, int] = {}
        for caller, _raw, _line in cg.unresolved:
            unresolved_by[caller] = unresolved_by.get(caller, 0) + 1
        for q in self.domain_fns:
            fn = prog.functions.get(q)
            if fn is None:
                continue
            self.dura_calls_total += len(fn.calls)
            self.dura_calls_unresolved += unresolved_by.get(q, 0)

    def unresolved_ratio(self) -> float:
        if not self.dura_calls_total:
            return 0.0
        return round(self.dura_calls_unresolved / self.dura_calls_total, 4)

    # -- HSL027: atomic-publish completeness -----------------------------------

    def atomic_publish_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program
        direct_flagged: set[str] = set()
        for s in self.sites:
            if s.ok or s.kind == "delegated":
                continue
            fn = prog.functions[s.fn]
            mod = prog.modules[fn.module]
            if _suppressed(mod, s.line, ATOMIC_PUBLISH):
                continue
            direct_flagged.add(s.fn)
            if s.kind == "publish":
                msg = (
                    f"durable publish under the {s.root!r} root in {s.fn} has "
                    f"no fsync before the rename — the new name can be durable "
                    f"before its bytes are, so a crash surfaces a zero-length "
                    f"or torn file; fsync the payload (and the directory) "
                    f"first (file_utils._overwrite_json is the idiom)"
                )
            else:
                msg = (
                    f"bare durable write under the {s.root!r} root in {s.fn} — "
                    f"a crash mid-write leaves a torn file at the final path; "
                    f"reach the mkstemp + fsync + os.replace idiom or delegate "
                    f"to file_utils.write_json (atomic-publish completeness, "
                    f"docs/static_analysis.md)"
                )
            out.append(Finding(
                mod.path, s.line, 0, ATOMIC_PUBLISH, msg,
                witness_paths=(mod.path,),
            ))
        for s in self.sites:
            if s.kind != "delegated" or s.ok:
                continue
            # The writer itself was already reported (or suppressed)
            # at its own site when it matched a root directly.
            if any(w in direct_flagged for w in s.chain):
                continue
            if any(
                d.fn in s.chain and d.kind != "delegated" and not d.ok
                for d in self.sites
            ):
                continue
            fn = prog.functions[s.fn]
            mod = prog.modules[fn.module]
            if _suppressed(mod, s.line, ATOMIC_PUBLISH):
                continue
            chain = " -> ".join((s.fn, *s.chain))
            witness = tuple(dict.fromkeys(
                prog.modules[prog.functions[q].module].path
                for q in (s.fn, *s.chain) if q in prog.functions
            ))
            out.append(Finding(
                mod.path, s.line, 0, ATOMIC_PUBLISH,
                f"durable write under the {s.root!r} root delegates through "
                f"{chain} but no function on the chain proves "
                f"fsync-before-publish — the delegation target writes the "
                f"final path bare; route it through the mkstemp + fsync + "
                f"os.replace idiom (file_utils.write_json)",
                witness_paths=witness,
            ))
        return out

    # -- HSL028: torn-window ordering ------------------------------------------

    def _build_window_proofs(self) -> dict[str, dict]:
        proofs: dict[str, dict] = {}
        prog = self.program
        for name in sorted(self.windows or ()):
            spec = self.windows[name]
            if len(spec) < 4:
                continue
            qname, first_pat, second_pat, point = spec[0], spec[1], spec[2], spec[3]
            fn = prog.functions.get(qname)
            proof = {
                "fn": qname, "live": fn is not None,
                "first": {"pattern": first_pat, "lines": []},
                "second": {"pattern": second_pat, "lines": []},
                "point": {"name": point, "line": None},
                "ordered": False, "proven": False,
            }
            proofs[name] = proof
            if fn is None:
                continue
            mod = prog.modules[fn.module]
            first_lines: list[int] = []
            second_lines: list[int] = []
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Call):
                    seg = _seg(mod, sub).lower()
                    # Only the call head: a match inside an argument
                    # (e.g. the second write passed a value derived
                    # from the first) must not move the window edge.
                    head = seg.split("(", 1)[0]
                    if first_pat.lower() in head or (
                        "." in first_pat and first_pat.lower() in seg
                    ):
                        first_lines.append(sub.lineno)
                    if second_pat.lower() in head:
                        second_lines.append(sub.lineno)
                elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = (
                        sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                    )
                    for tgt in targets:
                        seg = _seg(mod, tgt).lower()
                        if second_pat.lower() in seg:
                            second_lines.append(sub.lineno)
                        if first_pat.lower() in seg:
                            first_lines.append(sub.lineno)
            proof["first"]["lines"] = sorted(set(first_lines))
            proof["second"]["lines"] = sorted(set(second_lines))
            if not first_lines or not second_lines:
                continue
            lo, hi = max(first_lines), min(second_lines)
            proof["ordered"] = lo < hi
            for pname, pline, pkind in fn.fault_refs:
                if pkind == "point" and pname == point and lo < pline < hi:
                    proof["point"]["line"] = pline
                    break
            proof["proven"] = bool(
                proof["ordered"] and proof["point"]["line"] is not None
                and (self.known_points is None or not self.known_points
                     or point in self.known_points)
            )
        return proofs

    def torn_window_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program
        for name in sorted(self._window_proofs):
            proof = self._window_proofs[name]
            qname = proof["fn"]
            spec = self.windows[name]
            point = spec[3]
            if not proof["live"]:
                if not any(qname.startswith(m + ".") for m in prog.modules):
                    continue  # scanning a subset — out of scope
                out.append(Finding(
                    next(iter(prog.modules.values())).path, 0, 0, TORN_WINDOW,
                    f"stale TORN_WINDOWS entry: {name!r} names {qname} which "
                    f"is no function in the analyzed program — fix the qname "
                    f"or delete the window",
                ))
                continue
            fn = prog.functions[qname]
            mod = prog.modules[fn.module]
            if _suppressed(mod, fn.line, TORN_WINDOW):
                continue
            missing = []
            if not proof["first"]["lines"]:
                missing.append(
                    f"first write {proof['first']['pattern']!r} matches no "
                    f"call/assignment in {qname}")
            if not proof["second"]["lines"]:
                missing.append(
                    f"second write {proof['second']['pattern']!r} matches no "
                    f"call/assignment in {qname}")
            if proof["first"]["lines"] and proof["second"]["lines"] \
                    and not proof["ordered"]:
                missing.append(
                    f"the two writes are not statically ordered (a "
                    f"{proof['first']['pattern']!r} at line "
                    f"{max(proof['first']['lines'])} follows a "
                    f"{proof['second']['pattern']!r} at line "
                    f"{min(proof['second']['lines'])})")
            if proof["ordered"] and proof["point"]["line"] is None:
                missing.append(
                    f"no armed faults.fault_point({point!r}) strictly inside "
                    f"the window — the crash sweep cannot exercise the torn "
                    f"state")
            if self.known_points and point not in self.known_points:
                missing.append(
                    f"in-window point {point!r} is not declared in "
                    f"faults.KNOWN_POINTS")
            if missing:
                out.append(Finding(
                    mod.path, fn.line, 0, TORN_WINDOW,
                    f"torn window {name!r} ({spec[4] if len(spec) > 4 else ''})"
                    f" is unproven: " + "; ".join(missing) +
                    " (torn-window ordering, docs/static_analysis.md)",
                    witness_paths=(mod.path,),
                ))
        return out

    # -- HSL029: replay idempotence --------------------------------------------

    def _build_replay_closure(self) -> None:
        prog, cg = self.program, self.callgraph
        stack: list[str] = []
        for q in sorted(self.replay_roots or ()):
            if q in prog.functions and q not in self.replay_fns:
                self.replay_fns[q] = (q,)
                stack.append(q)
        while stack:
            q = stack.pop()
            for e in cg.out.get(q, []):
                for t in self._dispatch(e.callee):
                    if t in prog.functions and t not in self.replay_fns:
                        self.replay_fns[t] = (*self.replay_fns[q], t)
                        stack.append(t)

    def replay_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog = self.program
        for q, why in sorted((self.replay_roots or {}).items()):
            if q in prog.functions:
                continue
            if not any(q.startswith(m + ".") for m in prog.modules):
                continue
            out.append(Finding(
                next(iter(prog.modules.values())).path, 0, 0, REPLAY_IDEMPOTENCE,
                f"stale REPLAY_ROOTS entry: {q!r} names no function in the "
                f"analyzed program — fix the qname or delete the entry",
            ))
        for s in self.sites:
            chain = self.replay_fns.get(s.fn)
            if chain is None:
                continue
            atom = next((a for a in _NONDETERMINISTIC if a in s.seg), None)
            if atom is None:
                continue
            fn = prog.functions[s.fn]
            mod = prog.modules[fn.module]
            if _suppressed(mod, s.line, REPLAY_IDEMPOTENCE):
                continue
            witness = tuple(dict.fromkeys(
                prog.modules[prog.functions[c].module].path
                for c in chain if c in prog.functions
            ))
            out.append(Finding(
                mod.path, s.line, 0, REPLAY_IDEMPOTENCE,
                f"durable write on the replay path "
                f"{' -> '.join(chain)} derives its file name from "
                f"{atom!r} — a recovery/re-poll/takeover replay would write a "
                f"DIFFERENT path and orphan the first; derive the name from "
                f"cursor/log-id/generation values so the retry rewrites the "
                f"same file (replay idempotence, docs/static_analysis.md)",
                witness_paths=witness,
            ))
        return out

    # -- HSL030: snapshot-stamp discipline -------------------------------------

    def _snapshot_param(self, fn: FunctionInfo) -> str | None:
        args = fn.node.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            if a.arg in _SNAPSHOT_PARAMS:
                return a.arg
        return None

    @staticmethod
    def _default_fill_guarded(fn: FunctionInfo, mod: ModuleInfo) -> set[int]:
        """Node ids inside a conditional whose test is ``<own-param> is
        None`` — the default-fill override-point idiom (``stamp =
        live() if stamp is None else stamp``): the live read only fills
        an ABSENT argument, and a pinned caller passes the
        snapshot-derived value instead (run_query does exactly this),
        so the fallback is the sanctioned live context by construction."""
        args = fn.node.args
        params = {
            a.arg for a in (
                list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            )
        }
        guarded: set[int] = set()
        for sub in ast.walk(fn.node):
            if not isinstance(sub, (ast.If, ast.IfExp)):
                continue
            test_seg = _seg(mod, sub.test)
            if any(
                re.search(rf"\b{re.escape(p)}\s+is\s+(not\s+)?None\b", test_seg)
                for p in params
            ):
                for b in ast.walk(sub):
                    guarded.add(id(b))
        return guarded

    def snapshot_findings(self) -> list[Finding]:
        out: list[Finding] = []
        prog, cg = self.program, self.callgraph
        carriers = {
            q: p for q in sorted(prog.functions)
            if (p := self._snapshot_param(prog.functions[q])) is not None
        }
        self._carriers = sorted(carriers)

        def banned_what(sub: ast.AST) -> str | None:
            if isinstance(sub, ast.Call):
                tail = _dotted(sub.func).split(".")[-1]
                if tail in _LIVE_READ_TAILS:
                    return f"{tail}() live version read"
            elif isinstance(sub, ast.Attribute) and sub.attr == _LIVE_READ_ATTR:
                return f".{_LIVE_READ_ATTR} live version read"
            return None

        # Per-function digest, carrier-independent — computed ONCE and
        # shared by every carrier's closure walk: (unguarded banned
        # reads, unguarded resolved outgoing calls).
        digest: dict[str, tuple[list[tuple[int, str]], tuple[str, ...]]] = {}

        def fn_digest(cq: str) -> tuple[list[tuple[int, str]], tuple[str, ...]]:
            got = digest.get(cq)
            if got is not None:
                return got
            cfn = prog.functions[cq]
            cmod = prog.modules[cfn.module]
            cguard = self._default_fill_guarded(cfn, cmod)
            banned: list[tuple[int, str]] = []
            nexts: list[str] = []
            for sub in ast.walk(cfn.node):
                if id(sub) in cguard:
                    continue
                what = banned_what(sub)
                if what is not None:
                    banned.append((sub.lineno, what))
                    continue
                if isinstance(sub, ast.Call):
                    raw = _dotted(sub.func)
                    target = cg.resolve_call(cfn, raw) if raw else None
                    if target is None:
                        continue
                    for t in self._dispatch(target):
                        if t in prog.functions:
                            nexts.append(t)
            got = (banned, tuple(dict.fromkeys(nexts)))
            digest[cq] = got
            return got

        for q, param in sorted(carriers.items()):
            fn = prog.functions[q]
            mod = prog.modules[fn.module]
            guarded: set[int] = self._default_fill_guarded(fn, mod)
            for sub in ast.walk(fn.node):
                if isinstance(sub, (ast.If, ast.IfExp)):
                    test_seg = _seg(mod, sub.test)
                    if param in test_seg:
                        # A conditional dispatching on the snapshot
                        # parameter IS the sanctioned pinned-vs-live
                        # split — both branches are deliberate.
                        for b in ast.walk(sub):
                            guarded.add(id(b))
            calls_to_follow: list[tuple[str, int]] = []
            for sub in ast.walk(fn.node):
                if id(sub) in guarded:
                    continue
                what = banned_what(sub)
                if what is not None:
                    if not _suppressed(mod, sub.lineno, SNAPSHOT_STAMP):
                        out.append(Finding(
                            mod.path, sub.lineno, 0, SNAPSHOT_STAMP,
                            f"{what} inside the pinned-snapshot context of "
                            f"{q} — code reachable under run(plan, snapshot=) "
                            f"must key on the snapshot stamp, never the live "
                            f"version vector, or a pinned reader silently "
                            f"reads past its pin (snapshot-stamp discipline, "
                            f"docs/static_analysis.md)",
                            witness_paths=(mod.path,),
                        ))
                    continue
                if isinstance(sub, ast.Call):
                    raw = _dotted(sub.func)
                    got = cg.resolve_call(fn, raw) if raw else None
                    if (
                        got is not None
                        and got in prog.functions
                        and got not in carriers
                    ):
                        calls_to_follow.append((got, sub.lineno))
            # Unguarded closure: a live read two calls down is the same
            # bug — follow resolved non-carrier callees with a witness
            # chain (carriers prune: they are checked on their own).
            visited: set[str] = set(carriers)
            stack = [
                (callee, (q, callee)) for callee, _ in sorted(set(calls_to_follow))
            ]
            while stack:
                cq, chain = stack.pop()
                if cq in visited:
                    continue
                visited.add(cq)
                cfn = prog.functions.get(cq)
                if cfn is None:
                    continue
                cmod = prog.modules[cfn.module]
                banned, nexts = fn_digest(cq)
                for lineno, what in banned:
                    if _suppressed(cmod, lineno, SNAPSHOT_STAMP):
                        continue
                    witness = tuple(dict.fromkeys(
                        prog.modules[prog.functions[c].module].path
                        for c in chain if c in prog.functions
                    ))
                    out.append(Finding(
                        cmod.path, lineno, 0, SNAPSHOT_STAMP,
                        f"{what} reachable inside the pinned-snapshot "
                        f"context of {q} (via {' -> '.join(chain)}) — key "
                        f"on the snapshot stamp instead (snapshot-stamp "
                        f"discipline, docs/static_analysis.md)",
                        witness_paths=witness,
                    ))
                for t in nexts:
                    if t not in visited:
                        stack.append((t, (*chain, t)))
        return out

    # -- driver ----------------------------------------------------------------

    def findings(self) -> list[Finding]:
        if self._findings is None:
            out: list[Finding] = []
            if self.roots is not None:
                out += self.atomic_publish_findings()
            if self.windows is not None:
                out += self.torn_window_findings()
            if self.roots is not None and self.replay_roots is not None:
                out += self.replay_findings()
            out += self.snapshot_findings()
            self._findings = out
        return self._findings

    def to_json(self) -> dict:
        self.findings()  # materialize the carrier list
        roots_out: dict[str, dict] = {}
        for marker in sorted(self.roots or ()):
            roots_out[marker] = {
                "why": (self.roots or {}).get(marker, ""),
                "sites": [
                    {
                        "fn": s.fn, "line": s.line, "kind": s.kind,
                        "ok": s.ok,
                        **({"via": list(s.chain)} if s.chain else {}),
                    }
                    for s in sorted(
                        self.sites, key=lambda s: (s.fn, s.line)
                    ) if s.root == marker
                ],
            }
        return {
            "roots": roots_out,
            "domain_functions": {
                q: list(chain) for q, chain in sorted(self.domain_fns.items())
            },
            "windows": self._window_proofs,
            "replay": {
                q: {
                    "why": why,
                    "closure": sum(
                        1 for chain in self.replay_fns.values()
                        if chain[0] == q
                    ),
                    "sites": sorted(
                        {(s.fn, s.line) for s in self.sites
                         if self.replay_fns.get(s.fn, (None,))[0] == q},
                    ),
                }
                for q, why in sorted((self.replay_roots or {}).items())
            },
            "snapshot_carriers": list(getattr(self, "_carriers", [])),
            "unresolved": {
                "total": self.dura_calls_total,
                "unresolved": self.dura_calls_unresolved,
                "ratio": self.unresolved_ratio(),
            },
        }
