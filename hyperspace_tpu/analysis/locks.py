"""Lock-order and resource-safety analysis (rules HSL009 / HSL011).

**HSL009 lock-order inversion.** The serving PR put ~10 locks across the
session, metadata cache, device cache, scheduler and module memo caches;
nothing ran the ordering argument for them until now. This module builds
the static lock-acquisition graph: an edge ``A → B`` means some function
acquires (or calls a chain that acquires) lock ``B`` while holding
``A`` — where "holding" is an enclosing ``with A:`` and the chain runs
through the resolved call graph (analysis/callgraph.py). A cycle in that
graph is a potential deadlock under concurrent clients: thread 1 takes
``A`` and waits on ``B`` while thread 2 holds ``B`` and waits on ``A``.
Findings carry an **inline witness**: the two conflicting acquisition
chains, one per direction, each spelled as the `with` site plus the call
chain from it to the inner acquisition.

Self-edges (``A → A``) are reported only for non-reentrant ``Lock``s —
re-acquiring an ``RLock`` on the same thread is legal and the session
RLock does exactly that.

**HSL011 resource/exception safety.** Resources acquired outside a
``with``/``try-finally`` leak on the first exception between acquire and
release:

- ``lock.acquire()`` — including the ``acquire(timeout=...)`` /
  ``acquire(blocking=...)`` signature form, recognized whatever the
  receiver is named — with no ``release()`` in a ``finally`` of an
  enclosing ``try`` (use ``with lock:``);
- ``f = open(...)`` / ``os.fdopen(...)`` /
  ``tempfile.NamedTemporaryFile(...)`` with no ``with`` and no
  ``close()`` in a ``finally``;
- a tracer span / fault-injection context (``span(...)``, ``trace(...)``,
  ``faults.injected(...)``) created but never entered with ``with`` —
  the span would never close and the fault rule never reset.

Both rules run on the single-pass function summaries in
analysis/program.py; nothing here re-walks source.
"""

from __future__ import annotations

import ast
import dataclasses

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.lint import Finding
from hyperspace_tpu.analysis.program import FunctionInfo, LockDef, Program

LOCK_ORDER = "HSL009"
RESOURCE_SAFETY = "HSL011"

# Functions returning context managers that MUST be entered: creating
# one and dropping it silently discards the instrumentation/arming.
_CM_FACTORIES = {"span", "trace", "injected", "recording"}


@dataclasses.dataclass(frozen=True)
class LockEdge:
    """held → acquired, with the witness chain that produces it.

    ``chain`` is the call path from the function holding `held` to the
    function that acquires `acquired` (both inclusive); a direct nested
    ``with`` has a single-element chain."""

    held: str
    acquired: str
    holder_fn: str
    with_line: int
    chain: tuple[str, ...]
    acquire_line: int


class LockGraph:
    """The static lock-acquisition graph over a resolved Program."""

    def __init__(self, program: Program, callgraph: CallGraph | None = None):
        self.program = program
        self.callgraph = callgraph or CallGraph(program)
        # qname -> [(LockDef, line)] locks a function acquires directly
        self.direct: dict[str, list[tuple[LockDef, int]]] = {}
        self.edges: list[LockEdge] = []
        self._build()

    def _build(self) -> None:
        prog = self.program
        for fn in prog.functions.values():
            acquired = []
            for acq in fn.acquires:
                d = prog.resolve_lock(acq.ref, fn.module, fn.cls)
                if d is not None:
                    acquired.append((d, acq.line))
            if acquired:
                self.direct[fn.qname] = acquired
        # lock-holders: functions that directly acquire anything, plus the
        # set of locks transitively acquirable through each function.
        may = self._may_acquire()
        for fn in prog.functions.values():
            # (a) nested with: B acquired while A lexically held
            for acq in fn.acquires:
                inner = prog.resolve_lock(acq.ref, fn.module, fn.cls)
                if inner is None:
                    continue
                for href in acq.held:
                    outer = prog.resolve_lock(href, fn.module, fn.cls)
                    if outer is None:
                        continue
                    self.edges.append(LockEdge(
                        outer.lock_id, inner.lock_id, fn.qname,
                        href.line, (fn.qname,), acq.line,
                    ))
            # (b) call chains: a call made under `with A:` reaching a
            # function that acquires B
            for call in fn.calls:
                if not call.held:
                    continue
                callee = self.callgraph.resolve_call(fn, call.raw)
                if callee is None:
                    continue
                targets = {callee} | self.callgraph.reachable(callee)
                inner_locks: dict[str, tuple[str, int]] = {}
                for t in targets:
                    for d, line in self.direct.get(t, []):
                        inner_locks.setdefault(d.lock_id, (t, line))
                if not inner_locks:
                    continue
                for href in call.held:
                    outer = prog.resolve_lock(href, fn.module, fn.cls)
                    if outer is None:
                        continue
                    for lock_id, (t, line) in inner_locks.items():
                        path = self.callgraph.find_path(
                            callee, {q for q in targets if any(
                                d.lock_id == lock_id for d, _ in self.direct.get(q, [])
                            )},
                        ) or [callee]
                        self.edges.append(LockEdge(
                            outer.lock_id, lock_id, fn.qname,
                            href.line, (fn.qname, *path), line,
                        ))
        _ = may  # reserved: per-function may-acquire sets feed to_json()

    def _may_acquire(self) -> dict[str, set[str]]:
        """Fixpoint: every lock a function may acquire, directly or via
        any reachable callee."""
        out: dict[str, set[str]] = {}
        for q in self.program.functions:
            locks = {d.lock_id for d, _ in self.direct.get(q, [])}
            for r in self.callgraph.reachable(q):
                locks |= {d.lock_id for d, _ in self.direct.get(r, [])}
            if locks:
                out[q] = locks
        self.may_acquire = out
        return out

    # -- cycle detection ---------------------------------------------------
    def order_edges(self) -> dict[tuple[str, str], LockEdge]:
        """One representative witness per (held, acquired) pair, shortest
        chain first."""
        best: dict[tuple[str, str], LockEdge] = {}
        for e in self.edges:
            key = (e.held, e.acquired)
            if key not in best or len(e.chain) < len(best[key].chain):
                best[key] = e
        return best

    def inversions(self) -> list[Finding]:
        """HSL009 findings: every cycle in the lock-order graph, reported
        as its conflicting edge pairs with a two-chain witness. Self-edges
        are findings only for non-reentrant Locks."""
        best = self.order_edges()
        findings: list[Finding] = []
        seen_pairs: set[frozenset] = set()
        for (a, b), e in sorted(best.items()):
            if a == b:
                kind = self.program.locks[a].kind if a in self.program.locks else "Lock"
                if kind == "RLock":
                    continue
                findings.append(self._finding(e, e, self_cycle=True))
                continue
            rev = best.get((b, a))
            if rev is None:
                continue
            pair = frozenset((a, b))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            findings.append(self._finding(e, rev))
        # Longer cycles (A→B→C→A) without any 2-cycle: detect via SCC on
        # the order graph and report the component.
        findings.extend(self._multi_cycles(best, seen_pairs))
        return findings

    def _finding(self, e1: LockEdge, e2: LockEdge, self_cycle: bool = False) -> Finding:
        path = self._path_of(e1.holder_fn)
        if self_cycle:
            msg = (
                f"non-reentrant lock {e1.held} re-acquired while already held "
                f"(chain: {' -> '.join(e1.chain)} at line {e1.acquire_line}) — "
                f"this deadlocks the acquiring thread; use an RLock or split "
                f"the critical section"
            )
        else:
            msg = (
                f"lock-order inversion between {e1.held} and {e1.acquired}: "
                f"chain 1 holds {e1.held} (with at {e1.holder_fn}:{e1.with_line}) "
                f"then takes {e1.acquired} via {' -> '.join(e1.chain)}; "
                f"chain 2 holds {e2.held} (with at {e2.holder_fn}:{e2.with_line}) "
                f"then takes {e2.acquired} via {' -> '.join(e2.chain)} — two "
                f"threads interleaving these chains deadlock; impose one order "
                f"or drop the outer lock before the call"
            )
        # Witness files: every module on either chain — `--changed` must
        # keep the finding when the edit that created the cycle lives in
        # a callee, not at the reported with-site.
        witness = tuple(dict.fromkeys(
            self._path_of(q) for q in (*e1.chain, *e2.chain)
        ))
        return Finding(path, e1.with_line, 0, LOCK_ORDER, msg,
                       witness_paths=witness)

    def _multi_cycles(self, best, seen_pairs) -> list[Finding]:
        adj: dict[str, set[str]] = {}
        for (a, b) in best:
            if a != b:
                adj.setdefault(a, set()).add(b)
        sccs = _tarjan(adj)
        findings = []
        for comp in sccs:
            if len(comp) < 3:
                continue  # 2-cycles already reported with pair witnesses
            comp_set = set(comp)
            if any(frozenset(p) <= comp_set and len(frozenset(p)) == 2 for p in seen_pairs):
                continue
            ring = sorted(comp)
            edges = [
                best[(a, b)] for (a, b) in best
                if a in comp_set and b in comp_set and (a, b) in best
            ]
            e0 = edges[0]
            msg = (
                f"lock-order cycle through {len(ring)} locks: "
                f"{' -> '.join(ring)} -> {ring[0]}; first edge witness: "
                f"{' -> '.join(e0.chain)} (with at {e0.holder_fn}:{e0.with_line})"
            )
            findings.append(Finding(self._path_of(e0.holder_fn), e0.with_line, 0, LOCK_ORDER, msg))
        return findings

    def _path_of(self, fn_qname: str) -> str:
        fn = self.program.functions.get(fn_qname)
        if fn is None:
            return "<unknown>"
        mod = self.program.modules.get(fn.module)
        return mod.path if mod is not None else fn.module

    def to_json(self) -> dict:
        """Stable JSON: lock nodes and ordered edges (golden tests and
        the --format json report)."""
        return {
            "locks": {
                d.lock_id: d.kind for d in sorted(self.program.locks.values(), key=lambda x: x.lock_id)
            },
            "edges": sorted(
                {
                    (e.held, e.acquired, " -> ".join(e.chain))
                    for e in self.edges
                }
            ),
        }


def _tarjan(adj: dict[str, set[str]]) -> list[list[str]]:
    """Strongly connected components (iterative Tarjan)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(sorted(adj.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return out


# -- HSL011: resource / exception safety --------------------------------------

def resource_findings(program: Program) -> list[Finding]:
    findings: list[Finding] = []
    for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
        mod = program.modules.get(fn.module)
        if mod is None:
            continue
        findings.extend(_scan_function(fn, mod))
    return findings


def _scan_function(fn: FunctionInfo, mod) -> list[Finding]:
    """One function's HSL011 scan: runs on the already-parsed AST node
    kept by the program index (no re-parse)."""
    findings: list[Finding] = []
    node = fn.node
    with_ctx_calls: set[int] = set()
    finally_sources: list[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                for inner in ast.walk(item.context_expr):
                    if isinstance(inner, ast.Call):
                        with_ctx_calls.add(id(inner))
        elif isinstance(sub, ast.Try) and sub.finalbody:
            for stmt in sub.finalbody:
                for inner in ast.walk(stmt):
                    if isinstance(inner, ast.Attribute):
                        finally_sources.append(inner.attr)

    def _report(line: int, msg: str) -> None:
        text = mod.lines[line - 1] if 0 < line <= len(mod.lines) else ""
        if "# noqa" in text:
            tail = text.split("# noqa", 1)[1]
            if not tail.strip().startswith(":") or RESOURCE_SAFETY in tail:
                return
        findings.append(Finding(mod.path, line, 0, RESOURCE_SAFETY, msg))

    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        callee = ""
        if isinstance(sub.func, ast.Attribute):
            callee = sub.func.attr
        elif isinstance(sub.func, ast.Name):
            callee = sub.func.id
        # bare lock.acquire() with no release() in a finally. Name-based
        # recognition ("lock"/"cv" in the receiver) plus the signature
        # form: `.acquire(timeout=...)` / `.acquire(blocking=...)` is the
        # threading.Lock API whatever the variable is called — and the
        # timeout form is WORSE un-finallied, because the success branch
        # must conditionally release.
        if callee == "acquire" and isinstance(sub.func, ast.Attribute):
            base = sub.func.value
            base_txt = base.id if isinstance(base, ast.Name) else getattr(base, "attr", "")
            timed = any(kw.arg in ("timeout", "blocking") for kw in sub.keywords)
            if "lock" in base_txt.lower() or "cv" in base_txt.lower() or timed:
                if "release" not in finally_sources:
                    how = ".acquire(timeout=...)" if timed else ".acquire()"
                    _report(
                        sub.lineno,
                        f"{base_txt}{how} with no release() in a finally — "
                        f"an exception between acquire and release leaves the "
                        f"lock held forever; use `with {base_txt}:` (or "
                        f"try/finally with a conditional release for the "
                        f"timeout form)",
                    )
        # f = open(...) / os.fdopen(...) / tempfile.NamedTemporaryFile(...)
        # with no with / finally close — every descriptor producer leaks
        # the same way.
        elif (callee == "open" and isinstance(sub.func, ast.Name)) or callee in (
            "fdopen", "NamedTemporaryFile", "TemporaryFile",
        ):
            if id(sub) in with_ctx_calls:
                continue
            if _is_bound_without_close(node, sub) and "close" not in finally_sources:
                _report(
                    sub.lineno,
                    f"{callee}() bound to a name outside a with/try-finally — "
                    f"the descriptor leaks on any exception before close(); "
                    f"use `with {callee}(...) as f:`",
                )
        # span/trace/injected created but never entered
        elif callee in _CM_FACTORIES:
            if id(sub) in with_ctx_calls:
                continue
            if _is_discarded(node, sub):
                _report(
                    sub.lineno,
                    f"{callee}(...) returns a context manager that is never "
                    f"entered — the span/fault scope silently does nothing; "
                    f"use `with {callee}(...):`",
                )
    return findings


def _is_bound_without_close(fn_node: ast.AST, call: ast.Call) -> bool:
    """True when `call` is the value of a simple assignment whose target
    never has `.close()` called on every path — approximated as: no
    `<target>.close()` call anywhere in the function at all (a close on
    SOME path is accepted; flow-sensitivity isn't worth the false
    positives)."""
    target: str | None = None
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and sub.value is call and len(sub.targets) == 1:
            if isinstance(sub.targets[0], ast.Name):
                target = sub.targets[0].id
    if target is None:
        return False  # used inline (open(...).read()): GC-closed; HSL006 covers writes
    for sub in ast.walk(fn_node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "close"
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == target
        ):
            return False
    return True


def _is_discarded(fn_node: ast.AST, call: ast.Call) -> bool:
    """True when the CM-returning call is a bare expression statement —
    created, never entered, immediately dropped."""
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Expr) and sub.value is call:
            return True
    return False
