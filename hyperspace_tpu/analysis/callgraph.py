"""Project-wide call graph over the :class:`~.program.Program` index.

Resolution is deliberately *under*-approximate: an edge exists only when
the callee can be named with confidence. The strategies, in order:

1. ``self.m()`` → the enclosing class (walking program-local bases),
   and ``super().m()`` → the nearest base defining ``m``.
2. ``self.attr.m()`` / ``obj.m()`` where the attribute/variable has a
   known type binding (``self.attr = SomeClass(...)``, ``self.attr =
   param`` with an annotated parameter, a module-level ``X =
   SomeClass(...)``, a local ``x = SomeClass(...)`` / ``x = self.a.b``
   first binding, a factory or property whose return annotation names a
   program class) → that class's method. ``Ctor(...).m(...)`` — the
   immediate-invoke shape (``CreateAction(...).run()``) — types the
   receiver through the constructor the same way.
3. A bare or dotted name that resolves through the module's imports to a
   program function, class (→ ``__init__``), or module attribute —
   following one package re-export hop (``from pkg import X`` where
   ``pkg/__init__.py`` itself imports ``X``).
4. **Unique-method fallback**: ``anything.m()`` where exactly one class
   in the whole program defines ``m`` → that method. This is what
   connects ``session.run_query(...)`` in the scheduler to
   ``HyperspaceSession.run_query`` without type inference; ambiguous
   names (``get``, ``set``, ``clear``) resolve to nothing rather than
   to everything.

Unresolved calls are recorded (``CallGraph.unresolved``) so the
lock-order analysis can report its own blind spots, but they produce no
edges — the lock-graph stays free of speculative cycles.
"""

from __future__ import annotations

import collections
import dataclasses

from hyperspace_tpu.analysis.program import CallSite, FunctionInfo, Program

# Method names too generic for the unique-method fallback even if only
# one program class currently defines them — a new `get` somewhere must
# not silently rewire the graph.
_FALLBACK_BLOCKLIST = {
    "get", "set", "put", "add", "update", "pop", "clear", "append", "close",
    "run", "items", "keys", "values", "copy", "join", "split", "read", "write",
    # concurrent.futures / threading API names: `_pool.submit(...)` on a
    # ThreadPoolExecutor must not resolve to QueryServer.submit.
    "submit", "result", "shutdown", "wait", "notify", "start",
    # pyarrow API names: `writer.write_table(...)` on a pq.ParquetWriter
    # must not resolve to DeviceIndexBuilder.write_table — that edge
    # would drag the whole device build plane into the spawn-worker
    # domain (HSL019) through a receiver that is not even a program
    # class.
    "write_table",
    # file-object API: `fh.flush()` on an open file must not resolve to
    # RoutingLedger.flush — that edge would pull the ledger's persist
    # path (and its fault point) into every buffered-write caller's
    # error contract (HSL016).
    "flush",
}


@dataclasses.dataclass(frozen=True)
class Edge:
    caller: str
    callee: str
    line: int


class CallGraph:
    def __init__(self, program: Program):
        self.program = program
        self.edges: list[Edge] = []
        self.out: dict[str, list[Edge]] = collections.defaultdict(list)
        self.unresolved: list[tuple[str, str, int]] = []  # (caller, raw, line)
        self._build()

    # -- resolution --------------------------------------------------------
    def resolve_call(self, fn: FunctionInfo, raw: str) -> str | None:
        """The program-function qname `raw` refers to inside `fn`."""
        prog = self.program
        # Ctor(...).m(...): type the receiver through the constructor.
        if "()." in raw:
            ctor_raw, _, rest = raw.partition("().")
            cls_q = prog.class_of_ctor(fn.module, ctor_raw, fn=fn)
            if cls_q is not None and rest:
                return self._method_chain(cls_q, rest.split("."))
            return None
        parts = raw.split(".")
        # super().m() — resolved through the enclosing class's bases.
        if parts[0] == "super" and len(parts) == 2 and fn.cls is not None:
            for q in prog._mro(f"{fn.module}.{fn.cls}")[1:]:
                c = prog.classes.get(q)
                if c is not None and parts[1] in c.methods:
                    return c.methods[parts[1]].qname
            return None
        # self.m() / self.attr.m()
        if parts[0] == "self" and fn.cls is not None:
            cls_q = f"{fn.module}.{fn.cls}"
            if len(parts) == 2:
                m = self._class_method(cls_q, parts[1])
                if m is not None:
                    return m
                # self.attr() where attr is a typed attribute holding a
                # callable class instance — not a pattern used here; fall
                # through to the unique-method fallback.
            elif len(parts) >= 3:
                attr_type = self._attr_type(cls_q, parts[1])
                if attr_type is not None:
                    return self._method_chain(attr_type, parts[2:])
            return self._unique_method(parts[-1])
        # bare name: local/imported function or class constructor
        # (function-level imports consulted first — deferred-import idiom)
        target = prog.resolve_symbol(fn.module, parts[0], fn=fn)
        if target is not None:
            if len(parts) == 1:
                return self._callable_of(target)
            # module alias chain: obs_trace.span, config.KNOWN_KEYS, ...
            node = target
            for i, p in enumerate(parts[1:], start=1):
                if node in prog.modules:
                    mod = prog.modules[node]
                    if p in mod.functions and i == len(parts) - 1:
                        return mod.functions[p].qname
                    if p in mod.classes and i == len(parts) - 1:
                        return self._callable_of(mod.classes[p].qname)
                    if p in mod.var_types:
                        cls_q = prog.class_of_ctor(node, mod.var_types[p])
                        if cls_q is not None and i < len(parts) - 1:
                            return self._method_chain(cls_q, parts[i + 1:])
                    node = f"{node}.{p}" if f"{node}.{p}" in prog.modules else None
                    if node is None:
                        break
                elif node in prog.classes and i == len(parts) - 1:
                    return self._class_method(node, p)
                else:
                    break
        # local variable typed by its first binding: `x = Ctor(...)` /
        # `x = self.a.b` (the receiver-local shape the facade and the
        # executor use)
        if parts[0] in fn.local_types and len(parts) >= 2:
            src = fn.local_types[parts[0]]
            cls_q = None
            if src.endswith("()"):
                cls_q = prog.class_of_ctor(fn.module, src[:-2], fn=fn)
            elif src.startswith("self.") and fn.cls is not None:
                cls_q = f"{fn.module}.{fn.cls}"
                for attr in src.split(".")[1:]:
                    cls_q = self._attr_type(cls_q, attr) if cls_q else None
            if cls_q is not None:
                return self._method_chain(cls_q, parts[1:])
        # variable with a known module-level type in this module
        mod = prog.modules.get(fn.module)
        if mod is not None and parts[0] in mod.var_types and len(parts) >= 2:
            cls_q = prog.class_of_ctor(fn.module, mod.var_types[parts[0]])
            if cls_q is not None:
                return self._method_chain(cls_q, parts[1:])
        if len(parts) >= 2:
            return self._unique_method(parts[-1])
        return None

    def _callable_of(self, qname: str) -> str | None:
        prog = self.program
        if qname in prog.functions:
            return qname
        if qname in prog.classes:
            init = self._class_method(qname, "__init__")
            return init if init is not None else qname  # class w/o __init__: node anyway
        return None

    def _class_method(self, cls_q: str, method: str) -> str | None:
        for q in self.program._mro(cls_q):
            c = self.program.classes.get(q)
            if c is not None and method in c.methods:
                return c.methods[method].qname
        return None

    def _attr_type(self, cls_q: str, attr: str) -> str | None:
        for q in self.program._mro(cls_q):
            c = self.program.classes.get(q)
            if c is None:
                continue
            if attr in c.attr_types:
                return self.program.class_of_ctor(c.module, c.attr_types[attr])
            # A property/accessor whose return annotation names a program
            # class types the attribute access too (`def manager(self) ->
            # CachingIndexCollectionManager` — the lazy-init shape).
            m = c.methods.get(attr)
            if m is not None and m.returns_type:
                mod = self.program.modules.get(c.module)
                if mod is not None:
                    if m.returns_type in mod.classes:
                        return mod.classes[m.returns_type].qname
                    if m.returns_type in mod.imports:
                        t = mod.imports[m.returns_type]
                        if t in self.program.classes:
                            return t
        return None

    def _method_chain(self, cls_q: str, rest: list[str]) -> str | None:
        """Resolve `a.b.c` against a class: intermediate parts through
        typed attributes, the last part as a method."""
        node = cls_q
        for i, p in enumerate(rest):
            if i == len(rest) - 1:
                return self._class_method(node, p) or self._unique_method(p)
            nxt = self._attr_type(node, p)
            if nxt is None:
                return self._unique_method(rest[-1])
            node = nxt
        return None

    def _unique_method(self, method: str) -> str | None:
        if method.startswith("__") or method in _FALLBACK_BLOCKLIST:
            return None
        owners = self.program.classes_defining(method)
        if len(owners) == 1:
            return self._class_method(owners[0], method)
        return None

    # -- graph -------------------------------------------------------------
    def _build(self) -> None:
        for fn in self.program.functions.values():
            for call in fn.calls:
                callee = self.resolve_call(fn, call.raw)
                if callee is None:
                    self.unresolved.append((fn.qname, call.raw, call.line))
                elif callee != fn.qname:
                    e = Edge(fn.qname, callee, call.line)
                    self.edges.append(e)
                    self.out[fn.qname].append(e)

    def callees(self, qname: str) -> list[str]:
        return [e.callee for e in self.out.get(qname, [])]

    def reachable(self, start: str) -> set[str]:
        """Every function reachable from `start` (excluding start unless
        it is on a cycle)."""
        seen: set[str] = set()
        stack = [e.callee for e in self.out.get(start, [])]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(e.callee for e in self.out.get(q, []))
        return seen

    def find_path(self, start: str, targets: set[str]) -> list[str] | None:
        """Shortest call chain from `start` into any of `targets`
        (BFS; includes both endpoints). Used for witness reports."""
        if start in targets:
            return [start]
        prev: dict[str, str] = {}
        seen = {start}
        queue = collections.deque([start])
        while queue:
            q = queue.popleft()
            for e in self.out.get(q, []):
                if e.callee in seen:
                    continue
                prev[e.callee] = q
                if e.callee in targets:
                    path = [e.callee]
                    while path[-1] != start:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                seen.add(e.callee)
                queue.append(e.callee)
        return None

    def resolve_site(self, fn: FunctionInfo, call: CallSite) -> str | None:
        return self.resolve_call(fn, call.raw)

    def to_json(self) -> dict:
        """Stable JSON form (golden-file tests, --format json)."""
        edges = sorted({(e.caller, e.callee) for e in self.edges})
        return {
            "functions": sorted(self.program.functions),
            "edges": [list(e) for e in edges],
            "unresolved": sorted({raw for _, raw, _ in self.unresolved}),
        }
