"""Per-function effect summaries: shared-state reads/writes with locksets.

HSL009 proved the lock graph cycle-free — nothing deadlocks. This layer
answers the dual question: is every piece of shared state actually
TOUCHED under its lock? The raw material is the ``AttrAccess`` records
the single-pass function visitor already collects (analysis/program.py):
every ``self.<attr>`` load/store and module-global access, with the
stack of lock references lexically held at the site. This module turns
those into resolved, program-wide **effect summaries**:

- **State identity.** An instance attribute is ``(class qname, attr)``
  — attributed to the MRO class that assigns it, so a subclass method
  touching a base attribute shares the base's state id (the standard
  lockset abstraction, same as lock identity in program.py). A module
  global is ``(module, name)``. Locks themselves, and attributes bound
  to thread-safe sync primitives (``Event``, ``Queue``, ...), are not
  shared *data* and are excluded.
- **Effective locksets.** The lockset at an access is the lexically
  held set UNION the locks **guaranteed held on entry** to the function:
  ``H(g) = ⋂ over resolved call sites (H(caller) ∪ held-at-site)`` —
  a private helper only ever called under the cache lock is credited
  with it. The fixpoint intersects, so ONE unguarded call site strips
  the guarantee (under-approximate, like the call graph: missing edges
  can only hide protection, never invent it).
- **Propagated summaries.** Each function's transitive effect set —
  every (state, read|write, lockset) it can perform directly or through
  any resolved callee, with a shortest witness chain — propagated
  through the cross-module call graph to a fixpoint. The race rules
  (analysis/races.py) consume these; the ``racedemo`` golden JSON pins
  their exact shape.

Everything here is stdlib-only and never imports analyzed code, same as
the rest of the engine.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.program import FunctionInfo, Program

# Attribute constructor types that are synchronization primitives, not
# shared data: their cross-thread use is the point, not a race.
_SYNC_CTORS = {
    "Event", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "local",
}


@dataclasses.dataclass(frozen=True)
class ResolvedAccess:
    """One shared-state access with everything resolved: program-wide
    state id, the effective lockset (lexical ∪ entry-guaranteed), and
    where each guaranteed lock came from (witness material)."""

    state: str
    fn: str
    line: int
    write: bool
    keyed: bool
    in_init: bool
    lexical: frozenset[str]
    entry: frozenset[str]

    @property
    def locks(self) -> frozenset[str]:
        return self.lexical | self.entry


@dataclasses.dataclass(frozen=True)
class Effect:
    """One entry of a propagated summary: `fn` can perform this access
    (directly when ``chain == (fn,)``, else through the call chain)."""

    state: str
    write: bool
    locks: frozenset[str]
    line: int
    chain: tuple[str, ...]


class Effects:
    """Resolved shared-state accesses + entry-lock guarantees +
    propagated per-function effect summaries over a Program."""

    def __init__(self, program: Program, callgraph: CallGraph | None = None):
        self.program = program
        self.callgraph = callgraph or CallGraph(program)
        #: every resolved direct access, program-wide
        self.accesses: list[ResolvedAccess] = []
        #: state id -> its accesses (the HSL013 working set)
        self.by_state: dict[str, list[ResolvedAccess]] = {}
        #: fn qname -> locks guaranteed held on entry
        self.entry_locks: dict[str, frozenset[str]] = {}
        #: fn qname -> {lock id -> caller qname that guarantees it}
        self.entry_provider: dict[str, dict[str, str]] = {}
        self._summaries: dict[str, dict[tuple, Effect]] | None = None
        self._build()

    # -- state identity ----------------------------------------------------
    def state_of(self, fn: FunctionInfo, kind: str, attr: str) -> str | None:
        """The program-wide state id of an access, or None when the
        access is not shared data (locks, sync primitives, a ``self``
        access outside any class)."""
        prog = self.program
        if kind == "global":
            mod = prog.modules.get(fn.module)
            if mod is not None and attr in mod.module_locks:
                return None
            return f"{fn.module}.{attr}"
        if fn.cls is None:
            return None
        owner = f"{fn.module}.{fn.cls}"
        for cq in prog._mro(owner):
            c = prog.classes.get(cq)
            if c is None:
                continue
            if attr in c.attr_locks:
                return None  # the lock itself, not data
            if attr in c.attr_types and c.attr_types[attr].split(".")[-1] in _SYNC_CTORS:
                return None
            if attr in c.attr_names:
                return f"{cq}.{attr}"
        return f"{owner}.{attr}"

    # -- construction ------------------------------------------------------
    def _build(self) -> None:
        self._compute_entry_locks()
        for fn in self.program.functions.values():
            for acc in fn.attr_accesses:
                state = self.state_of(fn, acc.kind, acc.attr)
                if state is None:
                    continue
                lex = self._resolve_held(fn, acc.held)
                ra = ResolvedAccess(
                    state=state, fn=fn.qname, line=acc.line, write=acc.write,
                    keyed=acc.keyed, in_init=acc.in_init, lexical=lex,
                    entry=self.entry_locks.get(fn.qname, frozenset()),
                )
                self.accesses.append(ra)
                self.by_state.setdefault(state, []).append(ra)

    def _resolve_held(self, fn: FunctionInfo, held) -> frozenset[str]:
        out = set()
        for ref in held:
            d = self.program.resolve_lock(ref, fn.module, fn.cls)
            if d is not None:
                out.add(d.lock_id)
        return frozenset(out)

    def _compute_entry_locks(self) -> None:
        """Must-hold-on-entry fixpoint: a lock is guaranteed at entry to
        `g` iff EVERY resolved call site of `g` holds it (directly or by
        its own entry guarantee). Functions with no resolved callers are
        roots: nothing is guaranteed (a public API can always be called
        bare)."""
        prog, cg = self.program, self.callgraph
        in_edges: dict[str, list[tuple[str, frozenset[str]]]] = {}
        for fn in prog.functions.values():
            for call in fn.calls:
                callee = cg.resolve_call(fn, call.raw)
                # A callee can resolve to a class qname (no __init__);
                # only function nodes carry accesses.
                if callee is None or callee == fn.qname or callee not in prog.functions:
                    continue
                held = self._resolve_held(fn, call.held)
                in_edges.setdefault(callee, []).append((fn.qname, held))
        all_locks = frozenset(prog.locks)
        entry = {
            q: (all_locks if q in in_edges else frozenset())
            for q in prog.functions
        }
        changed = True
        while changed:
            changed = False
            for q, edges in in_edges.items():
                new = None
                for caller, held in edges:
                    ctx = entry.get(caller, frozenset()) | held
                    new = ctx if new is None else (new & ctx)
                if new is not None and new != entry[q]:
                    entry[q] = new
                    changed = True
        self.entry_locks = {q: s for q, s in entry.items() if s}
        # Witness material: for each guaranteed lock, one caller that
        # provides it (holds it lexically at the call site).
        for q, locks in self.entry_locks.items():
            prov: dict[str, str] = {}
            for caller, held in in_edges.get(q, []):
                for lock in locks:
                    if lock in held and lock not in prov:
                        prov[lock] = caller
            self.entry_provider[q] = prov

    # -- propagated summaries ----------------------------------------------
    def summaries(self) -> dict[str, dict[tuple, Effect]]:
        """fn qname -> {(state, write, locks): Effect} — the transitive
        effect set, propagated through the call graph to a fixpoint.
        A callee's effect lifted through a call site gains the locks
        held at that site; chains keep the shortest witness."""
        if self._summaries is not None:
            return self._summaries
        prog, cg = self.program, self.callgraph
        summ: dict[str, dict[tuple, Effect]] = {q: {} for q in prog.functions}
        for ra in self.accesses:
            key = (ra.state, ra.write, ra.locks)
            cur = summ[ra.fn].get(key)
            if cur is None:
                summ[ra.fn][key] = Effect(ra.state, ra.write, ra.locks, ra.line, (ra.fn,))
        changed = True
        while changed:
            changed = False
            for fn in prog.functions.values():
                mine = summ[fn.qname]
                for call in fn.calls:
                    callee = cg.resolve_call(fn, call.raw)
                    if callee is None or callee == fn.qname:
                        continue
                    held = self._resolve_held(fn, call.held)
                    for eff in list(summ.get(callee, {}).values()):
                        locks = eff.locks | held
                        key = (eff.state, eff.write, locks)
                        chain = (fn.qname, *eff.chain)
                        cur = mine.get(key)
                        if cur is None or len(chain) < len(cur.chain):
                            mine[key] = Effect(eff.state, eff.write, locks, eff.line, chain)
                            changed = True
        self._summaries = summ
        return summ

    def writes_reachable(self, fn_qname: str) -> list[Effect]:
        """Every write effect `fn` can perform, directly or transitively."""
        return [e for e in self.summaries().get(fn_qname, {}).values() if e.write]

    # -- report ------------------------------------------------------------
    def to_json(self) -> dict:
        """Stable JSON form (racedemo goldens, --format json report):
        per function, the direct reads/writes with their effective
        locksets, plus the entry-lock guarantees."""
        per_fn: dict[str, dict] = {}
        for ra in sorted(self.accesses, key=lambda a: (a.fn, a.line, a.state)):
            slot = per_fn.setdefault(ra.fn, {"reads": {}, "writes": {}})
            bucket = slot["writes" if ra.write else "reads"]
            locksets = bucket.setdefault(ra.state, [])
            locks = sorted(ra.locks)
            if locks not in locksets:
                locksets.append(locks)
        return {
            "functions": {q: per_fn[q] for q in sorted(per_fn)},
            "entry_locks": {
                q: sorted(s) for q, s in sorted(self.entry_locks.items())
            },
            "states": sorted(self.by_state),
        }
